"""Online serving layer: dynamic micro-batching over the vmapped
solvers.

Everything below the model layer in this framework is batch-first —
throughput on this hardware lives entirely in solving many independent
conditions as one device program (the batched-PSR GPGPU result,
arXiv:2005.11468). This package is the piece that FORMS those batches
from a live request stream, the same dynamic-batching shape every
inference stack has:

>>> from pychemkin_tpu import serve
>>> server = serve.ChemServer(mech, max_batch_size=32,
...                           max_delay_ms=2.0)
>>> server.warmup(["ignition"])          # compile the bucket ladder
>>> server.start()
>>> fut = server.submit_ignition(T0=1300.0, P0=1.01325e6, Y0=Y0,
...                              t_end=1e-3)
>>> fut.result().value["ignition_delay_ms"]

See :mod:`.server` for the full contract (admission control, bucket
ladder, rescue hand-off, graceful drain, telemetry).

The in-process core scales out over a process boundary:
:mod:`.transport` is a stdlib JSON-over-TCP front with multi-tenant
routing and per-tenant admission quotas, and :mod:`.supervisor` keeps
a transport backend process alive — crash/hang/poison detection,
budgeted respawn, in-flight re-submission (``BACKEND_LOST`` as data
when the budget is spent), graceful SIGTERM drain end-to-end.

Engine kinds are pluggable (:func:`.engines.register_engine`); the
neural surrogate fast path (:mod:`pychemkin_tpu.surrogate`) registers
``surrogate_ignition`` / ``surrogate_equilibrium`` engines that answer
verified predictions directly and re-enqueue misses to the wrapped
real engine through the rescue hand-off — statistically fast, never
wrong (see :class:`.engines.SurrogateEngine`).
"""

from .batcher import BatchPolicy
from .buckets import DEFAULT_BUCKETS, bucket_for, pad_indices
from .engines import (
    ENGINE_TYPES,
    DuplicateEngineKindError,
    EquilibriumEngine,
    EquilibriumSurrogateEngine,
    IgnitionEngine,
    IgnitionSurrogateEngine,
    PSREngine,
    SurrogateEngine,
    register_engine,
    registered_kinds,
)
from .errors import (
    ServeError,
    ServerClosed,
    ServerOverloaded,
    TransportClosed,
)
from .futures import Request, ServeFuture, ServeResult
from .server import ChemServer
from .supervisor import Supervisor, SupervisorError
from .transport import TransportClient, TransportServer

__all__ = [
    "BatchPolicy",
    "ChemServer",
    "DEFAULT_BUCKETS",
    "DuplicateEngineKindError",
    "ENGINE_TYPES",
    "EquilibriumEngine",
    "EquilibriumSurrogateEngine",
    "IgnitionEngine",
    "IgnitionSurrogateEngine",
    "PSREngine",
    "Request",
    "ServeError",
    "ServeFuture",
    "ServeResult",
    "ServerClosed",
    "ServerOverloaded",
    "Supervisor",
    "SupervisorError",
    "SurrogateEngine",
    "TransportClient",
    "TransportClosed",
    "TransportServer",
    "bucket_for",
    "pad_indices",
    "register_engine",
    "registered_kinds",
]
