"""Request/result plumbing: what a caller holds while the batch forms.

``submit_*`` returns a :class:`ServeFuture` immediately; the worker
resolves it with a :class:`ServeResult` after the micro-batch solves
(or after the rescue ladder finishes, for elements that failed the hot
path). A future only carries an EXCEPTION for infrastructure failures
(the batch solve itself raised, or the server was torn down without
drain); solver non-convergence is data — ``status`` — not an
exception, mirroring the per-element status contract of
:mod:`pychemkin_tpu.resilience`.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import itertools
import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

from ..resilience.status import name_of


class ServeResult(NamedTuple):
    """One request's outcome plus its serving metadata."""
    value: Dict[str, Any]    # per-kind result fields (see engines)
    status: int              # SolveStatus code after any rescue
    status_name: str
    ok: bool                 # status == OK
    rescued: bool            # failed hot path, fixed by the ladder
    rescue_rungs: int        # ladder rungs attempted (0 = hot path)
    kind: str
    bucket: int              # padded shape the batch solved at
    occupancy: int           # real requests in that batch
    queue_wait_ms: float     # submit -> batch formation
    solve_ms: float          # the batch's device solve wall time
    #: this lane's solver-physics profile (PYCHEMKIN_SOLVE_PROFILE:
    #: attempts / Newton iters / dt_min / stiffness, plus the rescue
    #: rung that finally resolved it); None when profiling is off or
    #: the kind carries no in-kernel profile. JSON-safe — rides the
    #: wire reply unchanged.
    profile: Optional[Dict[str, Any]] = None


def make_result(value: Dict[str, Any], status: int, *, kind: str,
                bucket: int, occupancy: int, queue_wait_ms: float,
                solve_ms: float, rescued: bool = False,
                rescue_rungs: int = 0,
                profile: Optional[Dict[str, Any]] = None
                ) -> ServeResult:
    status = int(status)
    return ServeResult(
        value=value, status=status, status_name=name_of(status),
        ok=status == 0, rescued=rescued, rescue_rungs=rescue_rungs,
        kind=kind, bucket=bucket, occupancy=occupancy,
        queue_wait_ms=round(queue_wait_ms, 3),
        solve_ms=round(solve_ms, 3), profile=profile)


class ServeFuture(concurrent.futures.Future):
    """A :class:`concurrent.futures.Future` resolving to a
    :class:`ServeResult`. ``result(timeout=...)`` blocks the caller,
    never the server."""


_req_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One admitted request, queued until a micro-batch adopts it."""
    kind: str
    key: Tuple                 # static group key (e.g. equilibrium option)
    payload: Dict[str, Any]    # normalized numeric payload
    future: ServeFuture
    t_submit: float            # time.perf_counter() at admission
    #: absolute time.perf_counter() deadline (None = no deadline): an
    #: expired request is dropped before dispatch and resolves with
    #: ``SolveStatus.DEADLINE_EXCEEDED`` — it never consumes a batch
    #: slot, and the rescue ladder starts no rung past it
    deadline: Optional[float] = None
    #: correlates a request across serve.rescue/serve.demux_error events
    id: int = dataclasses.field(
        default_factory=lambda: next(_req_counter))
    #: set by the worker BEFORE the rescue hand-off: from then on the
    #: rescue thread owns the future and crash cleanup must skip it
    handed_off: bool = False
    #: distributed-tracing id (None = unsampled: every span site takes
    #: the one-``if`` early-out); assigned at submit, propagated over
    #: the wire, shared by every span of this request's life
    trace_id: Optional[str] = None
    #: time.perf_counter() when the batcher adopted the request off the
    #: admission queue — splits queue wait into the admission span
    #: (submit → adopt) and the batch-window span (adopt → dispatch)
    t_adopt: Optional[float] = None

    def expired(self, now: Optional[float] = None) -> bool:
        """Whether the deadline has passed (False when none was set)."""
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) \
            >= self.deadline
