"""The retrain daemon: health signals in, shadowed candidates out.

One :class:`FlywheelDaemon` runs per fleet. Its reconciliation loop
(:meth:`poll` — same observed-state-vs-desired-state discipline as the
fleet autoscaler) watches the fleet health monitor for
``SURROGATE_RETRAIN`` (the hit-rate-collapse signal of
:mod:`pychemkin_tpu.health.signals`, kind-scoped via the evidence's
``req_kind``) and drives the full round:

1. **Retrain** (:meth:`retrain`): flush the miss bank, aim an
   active-learning sample box at the banked miss-condition hull (the
   densest miss region — new labels go where production traffic
   actually missed), label it through the durable sweep driver
   (:func:`~pychemkin_tpu.surrogate.dataset.generate_dataset` with an
   ``out_path``: checkpointed, SIGKILL-resumable), merge base + banked
   + active shards under the
   :func:`~pychemkin_tpu.surrogate.dataset.load_shards` signature
   checks, and fit a candidate with the INCUMBENT's architecture (same
   param-pytree structure = the promotion path re-uses every compiled
   program).
2. **Shadow** (:meth:`start_round` attaches): the candidate rides live
   traffic on every target, predicting and gating, never answering.
3. **Verdict** (:meth:`finish_round`): promotion fan-out or rejection
   via :func:`pychemkin_tpu.flywheel.promote.apply_verdict`; either
   way a typed ``flywheel.round`` event closes the round.

The daemon never imports the serve layer: targets are duck-typed
(``engine(kind)`` + ``promote_model(kind, model)`` — a
``ChemServer``), so it drives a single in-process server and a fleet
of transport-backed members identically.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import knobs, telemetry
from ..surrogate import dataset as sg_dataset
from ..surrogate import train as sg_train
from .shadow import ShadowEvaluator
from . import promote as fw_promote

#: the health signal that triggers a retrain round
RETRAIN_SIGNAL = "SURROGATE_RETRAIN"


def _pad_range(lo: float, hi: float, frac: float = 0.05):
    """A degenerate banked hull (one miss) still needs a samplable
    box: pad both ends by ``frac`` of the span (or of the value)."""
    lo, hi = float(lo), float(hi)
    span = max(hi - lo, abs(hi) * frac, 1e-12)
    return (lo - frac * span, hi + frac * span)


class FlywheelDaemon:
    """Drives retrain → shadow → verdict rounds for one fleet."""

    def __init__(self, mech, monitor, bank, targets: Sequence[Any], *,
                 kinds: Sequence[str] = ("ignition",),
                 model_dir: Optional[str] = None,
                 base_shards: Optional[Dict[str, List[str]]] = None,
                 recorder=None, train_kwargs: Optional[Dict] = None,
                 active_n: Optional[int] = None, seed: int = 0,
                 shadow_min_n: Optional[int] = None,
                 promote_margin: Optional[float] = None,
                 solver_kwargs: Optional[Dict[str, Dict]] = None,
                 base_box: Optional[Dict[str, Any]] = None):
        self.mech = mech
        self.monitor = monitor
        self.bank = bank
        self.targets = list(targets)
        self.kinds = tuple(kinds)
        self.model_dir = model_dir
        self.base_shards = dict(base_shards or {})
        self._rec = recorder if recorder is not None \
            else telemetry.MetricsRecorder()
        self.train_kwargs = dict(train_kwargs or {})
        self.active_n = int(active_n) if active_n is not None \
            else knobs.value("PYCHEMKIN_FLYWHEEL_ACTIVE_N")
        self.seed = int(seed)
        self.shadow_min_n = shadow_min_n
        self.promote_margin = promote_margin
        self.solver_kwargs = dict(solver_kwargs or {})
        #: per-kind starting SampleBox for the active-learning draw
        #: (axes the miss hull doesn't cover keep these values); kinds
        #: trained off the default box — e.g. a cold-inlet psr — pass
        #: theirs here so active labels stay on the incumbent's manifold
        self.base_box = dict(base_box or {})
        #: in-flight rounds: kind -> (candidate, ShadowEvaluator)
        self._shadows: Dict[str, Any] = {}
        self._round: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- plumbing --------------------------------------------------------
    def _engine(self, kind: str):
        return self.targets[0].engine(f"surrogate_{kind}")

    def incumbent(self, kind: str):
        return self._engine(kind).model

    def shadowing(self, kind: str) -> bool:
        with self._lock:
            return kind in self._shadows

    # -- active learning -------------------------------------------------
    def active_box(self, kind: str) -> sg_dataset.SampleBox:
        """The retrain draw's sample box: the banked miss-condition
        hull (padded) on every axis the sampler can target, the
        default box elsewhere — so generation concentrates labels in
        the region production traffic is actually missing in."""
        box = self.base_box.get(kind, sg_dataset.SampleBox())
        hull = self.bank.miss_box(kind)
        if not hull or not hull.get("n"):
            return box
        lo, hi = hull["lo"], hull["hi"]

        def rng(f):
            return _pad_range(lo[f], hi[f])

        if kind == "ignition":
            if "T0" in lo:
                box = box._replace(T=rng("T0"))
            if "P0" in lo:
                box = box._replace(P=rng("P0"))
            if "t_end" in hi:
                box = box._replace(t_end=float(hi["t_end"]))
        elif kind == "equilibrium":
            if "T" in lo:
                box = box._replace(T=rng("T"))
            if "P" in lo:
                box = box._replace(P=rng("P"))
        elif kind == "psr":
            if "tau" in lo:
                box = box._replace(tau=rng("tau"))
            if "P" in lo:
                box = box._replace(P=rng("P"))
        return box

    # -- the round -------------------------------------------------------
    def retrain(self, kind: str, *, scramble: bool = False):
        """Produce one candidate model for ``kind``; returns it.

        ``scramble=True`` is the chaos hook: the merged dataset's
        labels are permuted before the fit, yielding a
        plausible-shaped but WRONG candidate — the shadow gate must
        reject it (exercised by the soak's bad-candidate round)."""
        incumbent = self.incumbent(kind)
        gen = int(incumbent.meta.get("model_gen", 0))
        rnd = self._round.get(kind, 0)
        self._round[kind] = rnd + 1

        self.bank.flush(kind)
        box = self.active_box(kind)
        active_path = os.path.join(
            self.bank.root, f"active_{kind}_r{rnd:03d}.npz")
        os.makedirs(self.bank.root, exist_ok=True)
        sg_dataset.generate_dataset(
            self.mech, kind, n=self.active_n,
            seed=self.seed + 1000 * rnd, box=box,
            out_path=active_path, recorder=self._rec,
            solver_kwargs=self.solver_kwargs.get(kind))

        paths = (list(self.base_shards.get(kind, ()))
                 + self.bank.shard_paths(kind) + [active_path])
        data = sg_dataset.load_shards(
            paths, expect_mech_sig=self.bank.mech_sig)
        if scramble:
            rng = np.random.default_rng(self.seed + 7 * rnd)
            idx = np.flatnonzero(np.asarray(data["valid"], bool))
            y = np.array(data["y"])
            y[idx] = y[rng.permutation(idx)]
            data = dict(data, y=y)

        # the incumbent's architecture, member for member: same param
        # pytree structure means install_model re-uses every compiled
        # batch program (the zero-new-compiles promotion contract)
        kw = {"hidden": tuple(
                  int(h) for h in
                  str(incumbent.meta.get("hidden", "32,32")).split(",")),
              "steps": int(incumbent.meta.get("steps", 400)),
              "n_members": len(incumbent.members),
              "seed": self.seed + 1000 * rnd + 1}
        kw.update(self.train_kwargs)
        candidate, _curves = sg_train.fit_surrogate(data, **kw)
        return candidate._replace(
            meta={**candidate.meta, "model_gen": gen + 1})

    def start_round(self, kind: str, *, scramble: bool = False):
        """Retrain and attach the candidate as a shadow on every
        target; returns the candidate. No-op (returns the in-flight
        candidate) when a round is already riding."""
        with self._lock:
            inflight = self._shadows.get(kind)
        if inflight is not None:
            return inflight[0]
        candidate = self.retrain(kind, scramble=scramble)
        shadow = ShadowEvaluator(candidate, recorder=self._rec)
        for t in self.targets:
            t.engine(f"surrogate_{kind}").attach_shadow(shadow)
        with self._lock:
            self._shadows[kind] = (candidate, shadow)
        self._rec.inc("flywheel.rounds")
        return candidate

    def finish_round(self, kind: str) -> Optional[Dict[str, Any]]:
        """Conclude the in-flight round if the shadow has seen enough
        traffic: detach, promote or reject, emit ``flywheel.round``.
        Returns the summary, or None while undecided (shadow keeps
        riding) or when no round is in flight."""
        with self._lock:
            inflight = self._shadows.get(kind)
        if inflight is None:
            return None
        candidate, shadow = inflight
        if shadow.verdict(min_n=self.shadow_min_n,
                          margin=self.promote_margin) == "undecided":
            return None
        for t in self.targets:
            t.engine(f"surrogate_{kind}").detach_shadow()
        with self._lock:
            self._shadows.pop(kind, None)
        summary = fw_promote.apply_verdict(
            kind, candidate, shadow, self.targets,
            recorder=self._rec, model_dir=self.model_dir,
            min_n=self.shadow_min_n, margin=self.promote_margin)
        stats = summary["stats"]
        self._rec.event("flywheel.round", req_kind=kind,
                        verdict=summary["verdict"],
                        model_gen=summary["model_gen"],
                        n=stats["n"],
                        cand_hit_rate=round(stats["cand_hit_rate"], 4),
                        inc_hit_rate=round(stats["inc_hit_rate"], 4),
                        regressions=stats["regressions"])
        return summary

    # -- reconciliation --------------------------------------------------
    def poll(self) -> List[Dict[str, Any]]:
        """One reconciliation step: conclude any decided shadow round,
        then start rounds for every kind the health engine says needs
        one (``SURROGATE_RETRAIN``, kind-scoped via the evidence's
        ``req_kind``; an unscoped firing covers every configured
        kind). Returns the actions taken."""
        actions: List[Dict[str, Any]] = []
        for kind in self.kinds:
            if self.shadowing(kind):
                summary = self.finish_round(kind)
                if summary is not None:
                    actions.append({"action": "conclude", "kind": kind,
                                    "verdict": summary["verdict"]})
        wanted = set()
        for sig in self.monitor.firing():
            if sig.get("signal") != RETRAIN_SIGNAL:
                continue
            req_kind = (sig.get("evidence") or {}).get("req_kind")
            if req_kind is None:
                wanted.update(self.kinds)
            elif req_kind in self.kinds:
                wanted.add(req_kind)
        for kind in sorted(wanted):
            if not self.shadowing(kind):
                self.start_round(kind)
                actions.append({"action": "retrain", "kind": kind})
        return actions

    def run(self, stop_event: threading.Event,
            poll_s: Optional[float] = None) -> None:
        """Blocking reconciliation loop (run in a thread); one
        :meth:`poll` per ``PYCHEMKIN_FLYWHEEL_POLL_S``. A poll crash
        counts ``flywheel.errors`` and the loop keeps going — the
        flywheel degrades to static serving, never takes it down."""
        if poll_s is None:
            poll_s = knobs.value("PYCHEMKIN_FLYWHEEL_POLL_S")
        while not stop_event.is_set():
            try:
                self.poll()
            except Exception:
                self._rec.inc("flywheel.errors")
            stop_event.wait(float(poll_s))
