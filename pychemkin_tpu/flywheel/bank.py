"""Miss banking: the serving path's free training labels.

Every ``SURROGATE_MISS`` that rung 1 rescues pays for a real solve at
exactly the conditions where the model is weak — and then, without a
bank, throws the answer away. :class:`MissBank` is the capture hook the
surrogate engines call from the rescue path
(:meth:`pychemkin_tpu.serve.engines.SurrogateEngine.rescue_one` with
``bank=``): it turns the (payload, solver-verified value) pair into a
training row in the EXACT shard schema of
:mod:`pychemkin_tpu.surrogate.dataset`, so the retrain daemon merges
banked misses with base datasets through the same
:func:`~pychemkin_tpu.surrogate.dataset.load_shards` signature checks
that protect every other training input.

Trust properties:

- Only ``SolveStatus.OK`` labels bank (ignition additionally requires a
  detected ignition inside the horizon; psr requires Newton
  convergence) — a failed rescue is an incident, not a label.
- Every shard carries the serving mechanism's ``mech_sig``; the loaders
  refuse foreign shards, so a mechanism swap mid-run can never poison
  the training pool (:meth:`shard_paths` additionally filters, so
  stale-but-well-formed shards from a previous mechanism are skipped,
  not fatal).
- Shards bank atomically (tmp + rename via
  :func:`pychemkin_tpu.telemetry.atomic_savez`) and the per-kind ring
  budget (``PYCHEMKIN_FLYWHEEL_BANK_MAX_SHARDS``) evicts oldest-first,
  so the pool is bounded and a crash never leaves a torn shard.

A JSON sidecar per kind tracks the banked CONDITION box (payload-space
min/max of the dimensions the sampler can target) — the retrain
daemon's active-learning box, aimed at the densest miss region.
"""

from __future__ import annotations

import glob
import json
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .. import knobs, telemetry
from ..resilience import checkpoint
from ..resilience.status import SolveStatus
from ..surrogate import dataset as sg_dataset
from ..surrogate import model as sg_model

#: payload-space condition dimensions tracked per kind — the axes the
#: dataset sampler (:class:`~pychemkin_tpu.surrogate.dataset.SampleBox`)
#: can aim an active-learning draw at
CONDITION_FIELDS = {
    "ignition": ("T0", "P0", "t_end"),
    "equilibrium": ("T", "P"),
    "psr": ("tau", "P"),
}


class MissBank:
    """Bounded, signed, per-kind pool of rescued-miss training rows.

    ``root`` is the bank directory (created on first flush). Rows
    accumulate in memory and bank as one shard every ``shard_rows``
    rows (``PYCHEMKIN_FLYWHEEL_BANK_ROWS``); :meth:`flush` banks a
    partial shard on demand (the daemon calls it before a retrain).
    Thread-safe: ``note_miss`` arrives from rescue worker threads.
    """

    def __init__(self, root: str, mech, recorder=None, *,
                 max_shards: Optional[int] = None,
                 shard_rows: Optional[int] = None):
        self.root = root
        self.mech = mech
        self._rec = recorder if recorder is not None \
            else telemetry.MetricsRecorder()
        self.max_shards = int(max_shards) if max_shards is not None \
            else knobs.value("PYCHEMKIN_FLYWHEEL_BANK_MAX_SHARDS")
        self.shard_rows = int(shard_rows) if shard_rows is not None \
            else knobs.value("PYCHEMKIN_FLYWHEEL_BANK_ROWS")
        self.mech_sig = sg_dataset.mech_signature(mech)
        self._lock = threading.Lock()
        # per-kind pending rows: lists of (x, y, conditions) tuples
        self._pending: Dict[str, List] = {}
        self._option: Dict[str, int] = {}
        # next shard index per kind, resumed from what's on disk so a
        # restart appends after the newest shard instead of clobbering
        self._next_idx: Dict[str, int] = {}

    # -- capture (the serving-path hook) --------------------------------
    def note_miss(self, kind: str, payload: Dict[str, Any],
                  value: Dict[str, Any], *, status: int) -> bool:
        """Bank one rescued miss; returns True when the row was
        accepted. ``payload`` is the engine-normalized request,
        ``value`` the base engine's ``value_at`` of the rescue answer,
        ``status`` its ``SolveStatus``. Unlabelable rows (failed
        rescue, undetected ignition) are dropped — never trained on."""
        if int(status) != int(SolveStatus.OK):
            return False
        row = self._build_row(kind, payload, value)
        if row is None:
            return False
        with self._lock:
            self._pending.setdefault(kind, []).append(row)
            if kind == "equilibrium":
                self._option[kind] = int(payload.get("option", 1))
            n_pending = len(self._pending[kind])
            if n_pending >= self.shard_rows:
                self._flush_locked(kind)
        self._rec.inc("flywheel.banked")
        self._rec.inc(f"flywheel.banked.{kind}")
        return True

    def _build_row(self, kind, payload, value):
        if kind == "ignition":
            t = float(value.get("ignition_time_s", np.nan))
            t_end = float(payload["t_end"])
            if not (np.isfinite(t) and 0.0 < t < t_end):
                return None     # rescue answered, but no event to label
            x = np.asarray(sg_model.features(
                payload["T0"], payload["P0"], payload["Y0"]))
            y = np.array([np.log10(t)])
            cond = {"T0": float(payload["T0"]),
                    "P0": float(payload["P0"]), "t_end": t_end}
        elif kind == "equilibrium":
            X_eq = np.asarray(value["X"], np.float64)
            if not np.all(np.isfinite(X_eq)):
                return None
            Yn = np.asarray(payload["Y"], np.float64)
            Yn = Yn / max(Yn.sum(), 1e-30)
            x = np.asarray(sg_model.features(
                payload["T"], payload["P"], Yn))
            y = np.log(np.maximum(X_eq, sg_model.X_FLOOR))
            cond = {"T": float(payload["T"]), "P": float(payload["P"])}
        elif kind == "psr":
            if not bool(value.get("converged", False)):
                return None
            T_out = float(value["T"])
            Y_out = np.asarray(value["Y"], np.float64)
            if not (np.isfinite(T_out) and T_out > 0.0
                    and np.all(np.isfinite(Y_out))):
                return None
            x = np.asarray(sg_model.psr_features(
                payload["tau"], payload["P"], payload["Y_in"],
                payload["h_in"]))
            y = np.concatenate(
                [[T_out / sg_model.PSR_T_SCALE],
                 np.log(np.maximum(Y_out, sg_model.X_FLOOR))])
            cond = {"tau": float(payload["tau"]),
                    "P": float(payload["P"])}
        else:
            return None
        return (np.asarray(x, np.float64).ravel(),
                np.asarray(y, np.float64).ravel(), cond)

    # -- banking --------------------------------------------------------
    def flush(self, kind: Optional[str] = None) -> List[str]:
        """Bank pending rows now (all kinds, or one); returns the
        paths written. The daemon calls this before merging so a
        retrain sees every captured miss, not just full shards."""
        kinds = [kind] if kind is not None else sorted(self._pending)
        paths = []
        with self._lock:
            for k in kinds:
                p = self._flush_locked(k)
                if p is not None:
                    paths.append(p)
        return paths

    def _flush_locked(self, kind) -> Optional[str]:
        rows = self._pending.get(kind) or []
        if not rows:
            return None
        self._pending[kind] = []
        x = np.stack([r[0] for r in rows])
        y = np.stack([r[1] for r in rows])
        conds = [r[2] for r in rows]
        idx = self._next_idx.get(kind)
        if idx is None:
            idx = self._scan_next_index(kind)
        self._next_idx[kind] = idx + 1
        os.makedirs(self.root, exist_ok=True)
        path = os.path.join(self.root, f"miss_{kind}_{idx:05d}.npz")
        option = self._option.get(kind, -1)
        shard = {
            "v": sg_dataset.SHARD_VERSION, "kind": kind,
            # a bank shard's problem identity: captured live traffic,
            # not a sampled box — distinct by construction, and
            # load_shards only pins sig when asked to
            "sig": checkpoint.config_signature(
                "flywheel-miss-bank", kind, int(idx), int(option),
                tree=self.mech),
            "mech_sig": self.mech_sig,
            "x": x, "y": y,
            "valid": np.ones(x.shape[0], bool),
            # the trained-domain box this shard contributes: the hull
            # of its own rows (load_shards unions boxes across shards)
            "lo": x.min(axis=0), "hi": x.max(axis=0),
            "t_end": float(max((c.get("t_end", 0.0) for c in conds),
                               default=0.0)),
            "option": int(option),
            "status_counts": {str(int(SolveStatus.OK)): x.shape[0]},
        }
        sg_dataset.save_shard(path, shard)
        self._update_conditions_locked(kind, conds)
        self._evict_locked(kind)
        return path

    def _scan_next_index(self, kind) -> int:
        taken = [-1]
        for p in glob.glob(os.path.join(self.root,
                                        f"miss_{kind}_*.npz")):
            stem = os.path.basename(p)[:-4]
            try:
                taken.append(int(stem.rsplit("_", 1)[1]))
            except ValueError:
                continue
        return max(taken) + 1

    def _evict_locked(self, kind) -> None:
        paths = self._sorted_paths(kind)
        for p in paths[:max(0, len(paths) - self.max_shards)]:
            try:
                os.remove(p)
            except OSError:
                pass            # already gone — eviction is advisory

    def _sorted_paths(self, kind) -> List[str]:
        return sorted(glob.glob(
            os.path.join(self.root, f"miss_{kind}_*.npz")))

    # -- read side ------------------------------------------------------
    def shard_paths(self, kind: str,
                    mech_sig: Optional[str] = None) -> List[str]:
        """Banked shard paths for ``kind``, oldest first, SKIPPING any
        shard whose ``mech_sig`` disagrees with ``mech_sig`` (default:
        this bank's serving mechanism) — a leftover pool from a
        previous mechanism is ignored, not fatal."""
        want = mech_sig if mech_sig is not None else self.mech_sig
        out = []
        for p in self._sorted_paths(kind):
            try:
                with np.load(p, allow_pickle=False) as f:
                    if str(f["mech_sig"]) == want:
                        out.append(p)
            except (OSError, KeyError, ValueError):
                continue        # torn/foreign file: skip, don't poison
        return out

    def pending_rows(self, kind: str) -> int:
        with self._lock:
            return len(self._pending.get(kind) or [])

    def miss_box(self, kind: str) -> Optional[Dict[str, Any]]:
        """The banked condition hull for ``kind`` (payload-space
        min/max per :data:`CONDITION_FIELDS` axis plus the row count),
        or None before any flush — what the daemon aims the
        active-learning sample box at."""
        path = self._conditions_path(kind)
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _conditions_path(self, kind) -> str:
        return os.path.join(self.root, f"miss_{kind}_conditions.json")

    def _update_conditions_locked(self, kind, conds) -> None:
        fields = CONDITION_FIELDS.get(kind, ())
        cur = self.miss_box(kind) or {
            "n": 0, "lo": {}, "hi": {}}
        for c in conds:
            for f in fields:
                if f not in c:
                    continue
                v = float(c[f])
                cur["lo"][f] = min(cur["lo"].get(f, v), v)
                cur["hi"][f] = max(cur["hi"].get(f, v), v)
        cur["n"] = int(cur.get("n", 0)) + len(conds)
        path = self._conditions_path(kind)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cur, f, sort_keys=True)
        os.replace(tmp, path)
