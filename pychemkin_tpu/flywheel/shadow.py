"""Shadow evaluation: a candidate model rides live traffic, answers
nothing.

A retrained candidate is never trusted on its training metrics — it is
attached to the serving engine
(:meth:`pychemkin_tpu.serve.engines.SurrogateEngine.attach_shadow`),
which replays every accounted live batch through the candidate's
weights via ``predict_with`` (the SAME compiled program — a
same-architecture candidate adds zero XLA compiles to the hot path).
The shadow accumulates, per batch:

- would-have-hit: lanes the candidate's gate verifies,
- incumbent hits: lanes the serving model verified,
- **regressions**: lanes the incumbent verified but the candidate
  missed — the one number that must be ZERO for promotion (a flywheel
  round may only ADD coverage, never trade old hits for new ones),
- **cross-check disagreement**: on lanes where BOTH models claim a
  gate-verified answer, the mean distance between those answers in
  the model's target space (``engine.answer_array``). An ensemble
  retrained on poisoned labels agrees with itself — and so can pass
  the disagreement gate — but it cannot agree with the trusted
  incumbent; above ``PYCHEMKIN_FLYWHEEL_XCHECK_TOL`` the verdict is
  reject, whatever the hit counts say,
- gate-residual sums for both, for the artifact.

:meth:`verdict` turns the tallies into ``promote`` / ``reject`` /
``undecided`` under the ``PYCHEMKIN_FLYWHEEL_SHADOW_MIN_N`` sample
floor and ``PYCHEMKIN_FLYWHEEL_PROMOTE_MARGIN`` improvement margin.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from .. import knobs, telemetry
from ..surrogate import model as sg_model


class ShadowEvaluator:
    """Accumulates candidate-vs-incumbent gate outcomes over live
    batches. One instance may shadow several engines (a fleet): the
    tallies merge under the lock. Never raises out of
    ``observe_batch`` by contract of the engine hook (the engine wraps
    it anyway and counts ``flywheel.errors``)."""

    def __init__(self, model, recorder=None):
        self.model = model
        self._params = sg_model.model_params(model)
        self._rec = recorder if recorder is not None \
            else telemetry.MetricsRecorder()
        self._lock = threading.Lock()
        self.n = 0
        self.cand_hits = 0
        self.inc_hits = 0
        self.regressions = 0
        self._cand_resid = 0.0
        self._inc_resid = 0.0
        self._resid_n = 0
        self._xcheck_sum = 0.0
        self._xcheck_n = 0

    @property
    def model_gen(self) -> int:
        return int(self.model.meta.get("model_gen", 0))

    # -- the engine hook -------------------------------------------------
    def observe_batch(self, engine, key, payloads, bucket, out) -> None:
        """Replay one live batch through the candidate. ``out`` is the
        incumbent's result dict (bucket shape); only the real lanes
        are tallied."""
        cand = engine.predict_with(self._params, payloads, bucket, key)
        n = len(payloads)
        cand_ver = np.asarray(cand["verified"][:n], bool)
        inc_ver = np.asarray(out["verified"][:n], bool)
        cand_r = np.asarray(cand["residual"][:n], np.float64)
        inc_r = np.asarray(out["residual"][:n], np.float64)
        both = np.isfinite(cand_r) & np.isfinite(inc_r)
        # the cross-check: both-verified lanes carry two answers that
        # each passed a gate — per-lane mean |distance| in the model's
        # target space must be ~0 between honest models
        agree = cand_ver & inc_ver
        x_sum, x_n = 0.0, 0
        if agree.any():
            d = np.abs(engine.answer_array(cand, n)
                       - engine.answer_array(out, n)).mean(axis=1)
            lanes = agree & np.isfinite(d)
            x_sum, x_n = float(d[lanes].sum()), int(lanes.sum())
        with self._lock:
            self.n += n
            self.cand_hits += int(cand_ver.sum())
            self.inc_hits += int(inc_ver.sum())
            self.regressions += int((inc_ver & ~cand_ver).sum())
            self._cand_resid += float(cand_r[both].sum())
            self._inc_resid += float(inc_r[both].sum())
            self._resid_n += int(both.sum())
            self._xcheck_sum += x_sum
            self._xcheck_n += x_n
        self._rec.inc("flywheel.shadow.evals", n)

    # -- read side -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = self.n
            return {
                "n": n,
                "model_gen": self.model_gen,
                "cand_hits": self.cand_hits,
                "inc_hits": self.inc_hits,
                "regressions": self.regressions,
                "cand_hit_rate": self.cand_hits / n if n else 0.0,
                "inc_hit_rate": self.inc_hits / n if n else 0.0,
                "cand_mean_residual": (
                    self._cand_resid / self._resid_n
                    if self._resid_n else None),
                "inc_mean_residual": (
                    self._inc_resid / self._resid_n
                    if self._resid_n else None),
                "xcheck_n": self._xcheck_n,
                "xcheck_mean": (self._xcheck_sum / self._xcheck_n
                                if self._xcheck_n else None),
            }

    def verdict(self, *, min_n: Optional[int] = None,
                margin: Optional[float] = None) -> str:
        """``promote`` | ``reject`` | ``undecided``.

        - fewer than ``min_n`` shadowed requests → ``undecided`` (keep
          riding traffic; never judge on a handful of lanes);
        - ANY regression → ``reject`` (the incumbent's coverage is the
          floor — a candidate that trades hits is worse even if its
          total is higher);
        - cross-check disagreement above
          ``PYCHEMKIN_FLYWHEEL_XCHECK_TOL`` → ``reject`` (the
          candidate's verified answers contradict the incumbent's —
          a poisoned/scrambled model whose self-consistent ensemble
          fooled the gate);
        - otherwise promote iff the candidate's extra hits clear
          ``margin`` (a fraction of shadowed requests; default 0 means
          at least ONE strictly new verified answer).
        """
        if min_n is None:
            min_n = knobs.value("PYCHEMKIN_FLYWHEEL_SHADOW_MIN_N")
        if margin is None:
            margin = knobs.value("PYCHEMKIN_FLYWHEEL_PROMOTE_MARGIN")
        tol = knobs.value("PYCHEMKIN_FLYWHEEL_XCHECK_TOL")
        with self._lock:
            if self.n < int(min_n):
                return "undecided"
            if self.regressions > 0:
                return "reject"
            if (self._xcheck_n
                    and self._xcheck_sum / self._xcheck_n > float(tol)):
                return "reject"
            if self.cand_hits - self.inc_hits > float(margin) * self.n:
                return "promote"
            return "reject"
