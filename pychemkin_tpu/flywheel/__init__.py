"""The surrogate flywheel: production traffic trains the model.

Closes the loop PR 9 left open. The surrogate serving path already
produced everything a retrain needs — every ``SURROGATE_MISS`` is
rescued by the real solver (a free, solver-verified label at exactly
the conditions the model is weak on) and the health engine already
detects hit-rate collapse (``SURROGATE_RETRAIN``). This package wires
those ends together into an autonomous loop:

- :mod:`.bank` — :class:`~pychemkin_tpu.flywheel.bank.MissBank`
  captures rescued misses into signed dataset shards (the exact
  :mod:`pychemkin_tpu.surrogate.dataset` schema, atomic banking,
  per-kind ring budgets, mechanism-signature poison protection).
- :mod:`.daemon` — :class:`~pychemkin_tpu.flywheel.daemon
  .FlywheelDaemon` reconciles on the fleet health monitor's
  ``SURROGATE_RETRAIN`` (per-kind via evidence ``req_kind``), labels
  an active-learning box aimed at the banked miss hull through the
  durable sweep driver (SIGKILL-resumable), and fits candidates with
  the incumbent's architecture.
- :mod:`.shadow` — :class:`~pychemkin_tpu.flywheel.shadow
  .ShadowEvaluator` rides candidates on live traffic (same compiled
  programs, zero new XLA compiles; predicts + gates, never answers)
  and tallies would-have-hit vs the incumbent.
- :mod:`.promote` — :func:`~pychemkin_tpu.flywheel.promote
  .apply_verdict` promotes only a candidate that beats the incumbent
  hit rate with ZERO gate regressions — an atomic, versioned
  (``model_gen``) weight swap fanned out to every fleet member — and
  emits typed ``flywheel.promoted`` / ``flywheel.rejected`` events
  either way.

The serving guarantee is untouched: candidates never answer a request;
the verification gates stay between every model (incumbent or
promoted) and the client; a wrong-headed candidate (scrambled labels,
stale mechanism) dies in shadow or at the signature checks.
"""

from .bank import CONDITION_FIELDS, MissBank
from .daemon import RETRAIN_SIGNAL, FlywheelDaemon
from .promote import apply_verdict
from .shadow import ShadowEvaluator

__all__ = [
    "CONDITION_FIELDS",
    "FlywheelDaemon",
    "MissBank",
    "RETRAIN_SIGNAL",
    "ShadowEvaluator",
    "apply_verdict",
]
