"""Atomic promotion: the one gate between a candidate and production.

:func:`apply_verdict` reads the shadow tallies, decides, and acts:

- ``promote`` — fan the candidate out to every target server via
  ``promote_model`` (each engine's
  :meth:`~pychemkin_tpu.serve.engines.SurrogateEngine.install_model`
  swap: one attribute assignment under the engine lock, zero new XLA
  compiles for a same-architecture candidate), bank the promoted
  weights to the model directory for rollback, and emit ONE typed
  ``flywheel.promoted`` event carrying the shadow stats.
- ``reject`` — the incumbent keeps serving untouched; the candidate's
  weights are dropped and a typed ``flywheel.rejected`` event records
  why (the stats make the regression count auditable).
- ``undecided`` — nothing happens; the caller keeps shadowing.

Both terminal outcomes are events, not log lines: the acceptance
artifact asserts on them.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

from .. import telemetry
from ..surrogate import model as sg_model


def apply_verdict(kind: str, candidate, shadow,
                  targets: Sequence[Any], *, recorder=None,
                  model_dir: Optional[str] = None,
                  min_n: Optional[int] = None,
                  margin: Optional[float] = None) -> Dict[str, Any]:
    """Decide and act on one shadowed candidate; returns a summary
    dict (``verdict``, ``stats``, ``model_gen``, per-target install
    generations). ``targets`` are ``ChemServer``-shaped (duck-typed
    ``promote_model(kind, model)``); ``kind`` is the BASE request
    kind (``ignition``/...), promotion goes to ``surrogate_<kind>``.
    """
    rec = recorder if recorder is not None \
        else telemetry.MetricsRecorder()
    verdict = shadow.verdict(min_n=min_n, margin=margin)
    stats = shadow.stats()
    summary: Dict[str, Any] = {
        "kind": kind, "verdict": verdict, "stats": stats,
        "model_gen": int(candidate.meta.get("model_gen", 0)),
    }
    if verdict == "undecided":
        return summary

    if verdict == "promote":
        gens = []
        for t in targets:
            gens.append(int(t.promote_model(f"surrogate_{kind}",
                                            candidate)))
        summary["installed_gens"] = gens
        if model_dir is not None:
            # bank the promoted weights BEFORE declaring victory: the
            # rollback path (install gen N-1 by hand) needs the file
            os.makedirs(model_dir, exist_ok=True)
            path = os.path.join(
                model_dir, f"{kind}_gen{summary['model_gen']:03d}.npz")
            sg_model.save_model(path, candidate)
            summary["model_path"] = path
        rec.inc("flywheel.promoted")
        rec.event("flywheel.promoted", req_kind=kind,
                  model_gen=summary["model_gen"],
                  n=stats["n"], cand_hits=stats["cand_hits"],
                  inc_hits=stats["inc_hits"],
                  regressions=stats["regressions"],
                  xcheck_mean=stats.get("xcheck_mean"),
                  targets=len(targets))
    else:
        rec.inc("flywheel.rejected")
        rec.event("flywheel.rejected", req_kind=kind,
                  model_gen=summary["model_gen"],
                  n=stats["n"], cand_hits=stats["cand_hits"],
                  inc_hits=stats["inc_hits"],
                  regressions=stats["regressions"],
                  xcheck_mean=stats.get("xcheck_mean"))
    return summary
