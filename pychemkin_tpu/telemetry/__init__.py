"""Crash-safe telemetry: structured metrics + JSONL/atomic-snapshot
sinks.

Event schema (one JSON object per line in the sink):

``{"t": <unix seconds>, "kind": "<event kind>", ...fields}``

Kinds emitted by the framework:

- ``solve``        — one reactor-model solve (model, label, wall_s,
                     n_steps/n_rejected/n_newton, success, ...); the
                     same dict :meth:`ReactorModel.solve_report`
                     returns.
- ``odeint``       — host-side aggregate of a (possibly batched)
                     :class:`~pychemkin_tpu.ops.odeint.ODESolution`.
- ``flame``        — one :func:`~pychemkin_tpu.ops.flame1d.solve_flame`
                     driver run (per-stage wall time, regrids,
                     programs compiled).
- ``bench_config`` / ``bench_summary`` — benchmark ladder progress
                     (see ``pychemkin_tpu/benchmarks.py``; the summary
                     is also banked to an atomic snapshot after every
                     completed rung).
- ``checkpoint.save``   — one durable-sweep checkpoint bank landed
                     (label, path, done_upto, B); emitted by
                     ``resilience/checkpoint.py`` after every chunk.
- ``checkpoint.resume`` — a sweep job adopted banked work (label,
                     path, done_upto, B, resume_count).
- ``driver.retry``   — a sweep chunk failed and is being retried
                     (label, chunk, lo, hi, attempt, backoff_s,
                     error); see ``resilience/driver.py``.
- ``driver.reexec`` / ``driver.interrupted`` — the driver escalated a
                     poisoned backend to a subprocess re-exec / a
                     SIGTERM/SIGINT graceful shutdown banked and is
                     exiting with the resumable rc.
- ``checkpoint.save_failed`` / ``driver.reexec_failed`` — a bank could
                     not be written (durability degraded, job
                     continues) / an attempted re-exec's ``execvpe``
                     failed (the original chunk error propagates).
- ``serve.batch``    — one dispatched micro-batch of the online
                     serving layer (req_kind, key, occupancy, bucket,
                     solve_ms, n_rescue_handoff); see
                     ``pychemkin_tpu/serve/``.
- ``serve.rescue``   — one failed request finished the off-hot-path
                     rescue ladder (req_kind, rungs, rescued, status).
- ``serve.drain``    — the server shut down (drained, queue_depth).
- ``serve.batch_error`` / ``serve.worker_crashed`` — a batch solve
                     raised (futures carry the error, worker
                     survives) / the worker loop itself died (queued
                     futures failed, thread exits).
- ``serve.transport.drain`` — a transport backend drained its
                     ChemServers (every in-flight reply flushed)
                     before exiting; see
                     ``pychemkin_tpu/serve/transport.py``.
- ``supervisor.spawn``        — a supervised backend child came up
                     (generation, pid, port); generation > 0 is a
                     respawn (see ``pychemkin_tpu/serve/supervisor.py``).
- ``supervisor.backend_lost`` — the backend crashed, hung past the
                     heartbeat timeout, or answered with a
                     poisoned-client error (reason, rc, generation,
                     n_inflight).
- ``supervisor.respawn_exhausted`` — the respawn budget ran out: all
                     in-flight requests resolved with
                     ``SolveStatus.BACKEND_LOST`` as data.
- ``supervisor.drain``        — graceful supervisor shutdown
                     (graceful, respawns, resubmits, backend_lost).
- ``supervisor.kill_report`` / ``supervisor.kill_report_failed`` — the
                     supervisor banked a crash-flight-recorder kill
                     report for a lost backend (path, classification)
                     / could not write one (durability degraded, the
                     respawn continues).
- ``health.signal``  — a typed operator signal fired or cleared
                     (signal, severity, state, window_s, evidence,
                     fired_at, cleared_at); emitted by the
                     :mod:`pychemkin_tpu.health` rule engine from the
                     chemtop poll loop and the supervisor's health
                     sampler, so post-mortems and trace exemplars can
                     be correlated with what the fleet looked like.
- ``trace.span``     — one traced hop of one request (trace, span,
                     dur_ms, optional parent + per-span fields); see
                     :mod:`.trace` for the span-name catalogue and the
                     ``PYCHEMKIN_TRACE_SAMPLE`` sampling knob. The
                     event's ``t`` is the span END.

Histograms (``MetricsRecorder.observe``; p50/p95/p99 under
``histograms`` in ``snapshot()``): ``serve.queue_wait_ms``,
``serve.solve_ms``, ``serve.batch_occupancy``, and — when a surrogate
engine serves — ``serve.surrogate.residual`` (the verification gate's
residual / ensemble disagreement per live lane). The serving layer
also maintains the ``serve.queue_depth`` gauge and ``serve.requests``
/ ``serve.rejected`` / ``serve.deadline_expired`` / ``serve.batches``
/ ``serve.rescued`` / ``serve.abandoned`` / ``serve.status.<NAME>`` /
``serve.compiles[.*]`` counters; the transport layer adds
``serve.tenant_rejected[.<tenant>]`` (quota refusals), the supervisor
``supervisor.respawns`` / ``supervisor.resubmits`` /
``supervisor.backend_lost_requests``, and the surrogate fast path
``serve.surrogate.hit`` / ``serve.surrogate.miss`` (prediction failed
its gate) / ``serve.surrogate.fallback`` (miss re-solved on the real
engine) — ``hit + fallback`` accounts for every resolved surrogate
request except a miss whose fallback could not run (rescue disabled,
or the deadline expired before the fallback rung): those resolve
``SURROGATE_MISS`` with a NaN value and count as neither. The fleet
hit-rate gauge in ``tools/chemtop.py`` derives from the summed
counters.

Counters maintained on the default recorder include the pivot-free-LU
residual-check outcomes, bridged from device via
:func:`device_increment`: ``linalg.refine_stagnated`` counts SYSTEMS
whose refined solve failed the per-system residual check, while
``linalg.pivot_fallback`` counts SOLVES that took the pivoted-LU
fallback branch (a batched solve with several stagnated elements adds
several to the former, one to the latter).
"""

from . import trace
from .recorder import (
    Histogram,
    HistogramSubtractionError,
    MetricsRecorder,
    configure,
    device_counters_enabled,
    device_increment,
    flight_recorder_dump,
    flight_recorder_path,
    get_recorder,
    merge_histogram_states,
    record_event,
    subtract_histogram_states,
)
from .sink import (
    JsonlSink,
    append_jsonl,
    atomic_savez,
    atomic_write_json,
    dumps_line,
    read_jsonl,
)

__all__ = [
    "Histogram",
    "HistogramSubtractionError",
    "JsonlSink",
    "MetricsRecorder",
    "append_jsonl",
    "atomic_savez",
    "atomic_write_json",
    "configure",
    "device_counters_enabled",
    "device_increment",
    "dumps_line",
    "flight_recorder_dump",
    "flight_recorder_path",
    "get_recorder",
    "merge_histogram_states",
    "read_jsonl",
    "record_event",
    "subtract_histogram_states",
    "trace",
]
