"""Crash-safe on-disk telemetry sinks.

The round-5 bench artifact landed as ``rc=124, parsed: null`` because the
summary JSON was only printed at process exit — a killed worker left
nothing. The fix is a write discipline, not a format:

- **JSONL append** (:class:`JsonlSink`): one event per line, written with
  a single ``write()`` call on a line-buffered stream and flushed to the
  OS immediately. A SIGKILL can at worst truncate the LAST line; every
  earlier line stays parseable, so a killed process always leaves a
  usable event log (:func:`read_jsonl` skips a torn tail line).
- **Atomic snapshot rewrite** (:func:`atomic_write_json`): aggregate
  state (bench summaries, counter snapshots) is rewritten tmp+``rename``
  on every update, so the file on disk is always a COMPLETE JSON
  document — either the previous snapshot or the new one, never a
  half-written hybrid.

This module deliberately imports neither jax nor numpy: the sink must be
usable from orchestrator processes (bench parents, suite runners) that
never touch an accelerator, and must keep working while the accelerator
client is wedged.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional


def _json_default(obj: Any):
    """Best-effort encoder for numpy/jax scalars and arrays."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:   # noqa: BLE001 — fall through to repr
                break
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        return tolist()
    return repr(obj)


def dumps_line(obj: Dict[str, Any]) -> str:
    """One compact JSON line (no embedded newlines)."""
    return json.dumps(obj, default=_json_default,
                      separators=(",", ":"))


def atomic_write_json(path: str, obj: Any) -> str:
    """Rewrite ``path`` atomically (tmp + ``os.replace``); the file is
    always a complete JSON document even across a concurrent kill.
    The tmp name is unique per (process, thread): two threads
    snapshotting at once must not truncate each other's tmp mid-write
    and race the rename."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(obj, default=_json_default, indent=1))
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def atomic_savez(path: str, **arrays: Any) -> str:
    """:func:`atomic_write_json`'s discipline applied to npz payloads
    (tmp unique per process+thread, fsync'd, ``os.replace``) — the ONE
    place the array-artifact atomicity recipe lives (checkpoint
    manifests, surrogate shards/models). A concurrent kill leaves
    either the old complete file or a torn tmp, never a half-written
    ``path``."""
    import numpy as np

    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}.npz"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def append_jsonl(path: str, obj: Dict[str, Any]) -> None:
    """One-shot crash-safe append of a single event (opens/closes the
    file; use :class:`JsonlSink` for streams of events)."""
    line = dumps_line(obj) + "\n"
    with open(path, "a") as f:
        f.write(line)
        f.flush()


def read_jsonl(path: str) -> Iterator[Dict[str, Any]]:
    """Yield events from a JSONL file, skipping a torn final line (the
    one write a SIGKILL can truncate)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue


class JsonlSink:
    """Line-buffered JSONL event sink with an optional atomic snapshot
    companion.

    ``emit`` writes one event line and flushes; ``write_snapshot``
    rewrites ``<path>.snapshot.json`` (or ``snapshot_path``) atomically.
    Safe to ``emit`` after ``close`` (reopens in append mode), so a
    long-lived recorder survives its sink being rotated.
    """

    def __init__(self, path: str, snapshot_path: Optional[str] = None):
        self.path = path
        self.snapshot_path = snapshot_path or (path + ".snapshot.json")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def emit(self, event: Dict[str, Any]) -> None:
        if self._f is None or self._f.closed:
            self._f = open(self.path, "a", buffering=1)
        self._f.write(dumps_line(event) + "\n")
        self._f.flush()

    def write_snapshot(self, obj: Any) -> str:
        return atomic_write_json(self.snapshot_path, obj)

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def timestamp() -> float:
    """Wall-clock seconds; isolated here so tests can monkeypatch one
    place."""
    return time.time()
