"""Lightweight distributed request tracing over the JSONL event spine.

The serving stack spans four processes per request (client → TCP
transport → supervised backend → rescue thread, plus supervisor
respawn/re-submission); when a request is slow, deadline-expired, or
resolved ``BACKEND_LOST``, the per-process counters cannot say *where*
the time or the loss went. This module adds the missing primitive: a
**span** — one named, timed hop of one request — emitted as a
``trace.span`` event through the existing crash-safe sink, so the full
story of a request is reconstructable by grepping its trace id across
the client / backend / supervisor JSONL files (and survives a SIGKILL
mid-request, because every span already written is its own line).

Design constraints, in order:

- **Cheap when off.** Sampling is decided ONCE per request at submit
  (``new_trace_id`` returns ``None`` for unsampled requests); every
  instrumentation site takes the ``trace_id is None`` early-out, so an
  unsampled request pays one ``if`` per hop — no dict builds, no JSON.
- **No clock coupling.** Spans carry a duration; the event's own
  wall-clock stamp ``t`` is the span's END, so ``start = t - dur_ms/1e3``
  without requiring processes to share a monotonic clock.
- **Schema = event schema.** A span is a plain recorder event
  (``{"t", "kind": "trace.span", "trace", "span", "dur_ms", ...}``), so
  the sink's torn-tail tolerance, the recorder's in-memory tail, and
  ``read_jsonl`` all apply unchanged.

Span names emitted by the framework (all carry ``trace``/``dur_ms``):

=========================  =============================================
``client.wire``            one wire round-trip as the TransportClient
                           saw it (submit frame → result/error reply)
``serve.admission``        submit → the batcher adopted the request
``serve.batch_window``     adoption → the micro-batch group dispatched
``serve.dispatch``         the padded program ran (fields: req_kind /
                           bucket / occupancy / compile_hit / lane /
                           status)
``serve.expired``          the request was dropped at the deadline gate
``serve.surrogate``        the surrogate fast path's verdict on this
                           request (fields: verified / residual) —
                           emitted alongside ``serve.dispatch`` for
                           surrogate-kind requests
``serve.rescue_rung``      one rescue-ladder rung re-solve (fields:
                           level / status)
``rescue.rung``            one batch-sweep rescue rung
                           (:func:`~pychemkin_tpu.resilience.rescue
                           .run_rescue` with a ``trace_id``)
``supervisor.resubmit``    the supervisor re-sent an in-flight request
                           to a respawned backend (fields: generation /
                           attempt) — the child span that makes a
                           healed request show its dead generation
``supervisor.backend_lost``  the request resolved ``BACKEND_LOST``
                           (fields: generation)
=========================  =============================================

Sampling knob: ``PYCHEMKIN_TRACE_SAMPLE`` ∈ [0, 1] — the probability a
submit draws a trace id. Default 1.0 (every request traced): tests and
chaos soaks want the full story, and the serve bench's
``trace_overhead_pct`` bounds the cost. Production fleets at high rates
should export e.g. ``PYCHEMKIN_TRACE_SAMPLE=0.01``; the env var is read
per draw, so a live process can be re-sampled via its environment
without restart.
"""

from __future__ import annotations

import contextlib
import os
import random
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional

from .. import knobs
from .sink import read_jsonl

#: sampling probability env knob (see module docstring)
TRACE_SAMPLE_ENV = "PYCHEMKIN_TRACE_SAMPLE"

#: the event kind every span is emitted as
SPAN_KIND = "trace.span"

#: sentinel default for ``trace_id=`` kwargs: "the caller expressed no
#: decision — draw one here". Distinct from an EXPLICIT ``None``
#: ("upstream sampled this request out"), which must propagate through
#: every hop without being re-drawn — otherwise a fleet at
#: ``PYCHEMKIN_TRACE_SAMPLE=0.5`` would re-roll the dice per hop and
#: emit orphan backend-only trace fragments no client record names.
UNSET = object()


def resolve_trace_id(trace_id) -> Optional[str]:
    """The one place the draw-vs-propagate rule lives: a caller that
    passed nothing (``UNSET``) gets a fresh sampling draw; an explicit
    id — including an explicit unsampled ``None`` — passes through."""
    return new_trace_id() if trace_id is UNSET else trace_id


def sample_rate() -> float:
    """The configured sampling probability, clamped to [0, 1]
    (unparseable values fall back to the default 1.0). Read through
    the knob registry PER CALL, so a live process is re-sampled via
    its environment without restart."""
    return knobs.value(TRACE_SAMPLE_ENV)


def new_trace_id() -> Optional[str]:
    """Draw one request's trace id, or ``None`` when the sampling rate
    says skip — the single decision every downstream span site keys on
    (``None`` propagates through the wire and disables every hop's
    emission with one ``if``)."""
    rate = sample_rate()
    if rate <= 0.0:
        return None
    if rate < 1.0 and random.random() >= rate:
        return None
    return uuid.uuid4().hex[:16]


def emit_span(recorder, trace_id: Optional[str], span_name: str,
              dur_ms: float, parent: Optional[str] = None,
              **fields: Any) -> Optional[Dict[str, Any]]:
    """Emit one span event on ``recorder`` (no-op for an unsampled —
    ``None`` — trace id). The event's ``t`` stamp is the span END."""
    if trace_id is None:
        return None
    if parent is not None:
        fields["parent"] = parent
    return recorder.event(SPAN_KIND, trace=trace_id, span=span_name,
                          dur_ms=round(float(dur_ms), 3), **fields)


@contextlib.contextmanager
def span(recorder, trace_id: Optional[str], span_name: str,
         parent: Optional[str] = None, **fields: Any):
    """Time a block as one span (no-op when ``trace_id`` is None)."""
    if trace_id is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        emit_span(recorder, trace_id, span_name,
                  (time.perf_counter() - t0) * 1e3, parent, **fields)


# ---------------------------------------------------------------------------
# reconstruction (offline: tests, chemtop, loadgen exemplars, humans)

def spans_from_events(events: Iterable[Dict[str, Any]],
                      trace_id: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, Any]]]:
    """Group ``trace.span`` events by trace id (optionally only
    ``trace_id``), each list sorted by span START (``t - dur_ms/1e3``)
    so the request's story reads top to bottom."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        if ev.get("kind") != SPAN_KIND:
            continue
        tid = ev.get("trace")
        if tid is None or (trace_id is not None and tid != trace_id):
            continue
        out.setdefault(tid, []).append(ev)
    for spans_ in out.values():
        spans_.sort(key=lambda ev: (float(ev.get("t", 0.0))
                                    - float(ev.get("dur_ms", 0.0)) / 1e3))
    return out


def load_trace(paths, trace_id: str) -> List[Dict[str, Any]]:
    """One request's spans, gathered across JSONL sink files (client /
    backend / supervisor), start-sorted. Missing files are skipped —
    a single-process setup has fewer sinks, not an error."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    events: List[Dict[str, Any]] = []
    for p in paths:
        try:
            events.extend(read_jsonl(os.fspath(p)))
        except FileNotFoundError:
            continue
    return spans_from_events(events, trace_id).get(trace_id, [])


def breakdown(spans: Iterable[Dict[str, Any]]) -> Dict[str, float]:
    """Per-stage time attribution: span name -> total ``dur_ms``
    (a span name appearing twice — e.g. two rescue rungs — sums)."""
    out: Dict[str, float] = {}
    for ev in spans:
        name = ev.get("span", "?")
        out[name] = round(out.get(name, 0.0)
                          + float(ev.get("dur_ms", 0.0)), 3)
    return out
