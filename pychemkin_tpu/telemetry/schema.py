"""Canonical telemetry name schema — every counter/gauge/histogram/
event/span name the framework emits, in one place.

This module is the contract between the emitting code and everything
downstream of it: chemtop's fleet merge, the bench artifacts, the
flight recorder, test assertions, and human grep. The ``chemlint``
static pass (:mod:`pychemkin_tpu.lint`) enforces it in BOTH
directions without importing this module (pure AST extraction — only
literal tuples may live here):

- every string-literal name at an emit site (``inc``/``gauge``/
  ``observe``/``event``/``emit_span``/``device_increment``/...) must
  be an exact entry or extend a registered ``*_PREFIXES`` family;
- every entry here must still be referenced somewhere in the tree —
  deleting an emitting subsystem forces the schema (and dashboards)
  to shrink with it.

Dynamic families (``serve.status.<NAME>``, ``odeint.status.<NAME>``,
per-tenant / per-kind / per-bucket series) are declared as prefixes:
the runtime suffix is data (a status name, a tenant, a bucket), the
prefix is schema.

The scheduling package's exported ``SCHEDULE_COUNTERS`` tuple is
cross-checked as a subset of :data:`COUNTERS` by the lint, so the two
cannot drift.
"""

from __future__ import annotations

# -- counters ---------------------------------------------------------------

COUNTERS = (
    "checkpoint.resumes",
    "checkpoint.save_failures",
    "checkpoint.saves",
    "driver.retries",
    "flame.programs_built",
    "fleet.http.requests",
    "fleet.http.rejected",
    "fleet.hedge.issued",
    "fleet.hedge.won",
    "fleet.hedge.wasted",
    "fleet.journal.appends",
    "fleet.journal.duplicates",
    "fleet.journal.replayed",
    "fleet.rejected",
    "fleet.requests",
    "fleet.reroutes",
    "flame.solves",
    "flywheel.banked",
    "flywheel.errors",
    "flywheel.promoted",
    "flywheel.rejected",
    "flywheel.rounds",
    "flywheel.shadow.evals",
    "linalg.pivot_fallback",
    "linalg.refine_stagnated",
    "model.failed_solves",
    "model.solves",
    "network.cluster_reject",
    "odeint.newton",
    "odeint.newton_untracked",
    "odeint.rejected",
    "odeint.solves",
    "odeint.stalled",
    "odeint.steps",
    "program.compiles",
    "resilience.abandoned",
    "resilience.rescued",
    "schedule.cohorts",
    "schedule.compactions",
    "schedule.mesh_rebins",
    "schedule.ladder_adjust",
    "serve.abandoned",
    "serve.batch_errors",
    "serve.batches",
    "serve.compiles",
    "serve.deadline_expired",
    "serve.rejected",
    "serve.requests",
    "serve.rescued",
    "serve.surrogate.fallback",
    "serve.surrogate.hit",
    "serve.surrogate.miss",
    "serve.tenant_rejected",
    "serve.transport.reply_dropped",
    "supervisor.backend_lost_requests",
    "supervisor.respawns",
    "supervisor.resubmits",
    "staging.cache_corrupt",
    "staging.cache_hit",
    "staging.emit",
    "staging.fused_built",
    "staging.fused_hit",
    "staging.hit",
    "staging.memo_hit",
)

#: dynamic counter families: the suffix is runtime data (a status
#: name, an engine kind, a tenant id)
COUNTER_PREFIXES = (
    "flywheel.banked.",
    "model.status.",
    "odeint.newton.",
    "odeint.status.",
    "program.compiles.",
    "resilience.status.",
    "serve.compiles.",
    "serve.status.",
    "serve.surrogate.fallback.",
    "serve.surrogate.hit.",
    "serve.surrogate.miss.",
    "serve.tenant_rejected.",
)

# -- gauges -----------------------------------------------------------------

GAUGES = (
    "fleet.pool_size",
    "schedule.predictor_corr",
    "serve.queue_depth",
)

GAUGE_PREFIXES = ()

# -- histograms -------------------------------------------------------------

HISTOGRAMS = (
    "serve.batch_occupancy",
    "serve.queue_wait_ms",
    "serve.solve_ms",
    "serve.surrogate.residual",
    "solve.dt_min_ns",
    "solve.newton_per_attempt",
    "solve.steps_per_lane",
    "sweep.solve_ms",
)

#: per-bucket occupancy distributions: serve.occupancy.b<bucket>;
#: per-compiled-program wall time: program.wall_ms.<program_id>
HISTOGRAM_PREFIXES = (
    "program.wall_ms.",
    "serve.occupancy.b",
)

# -- events -----------------------------------------------------------------

EVENTS = (
    "bench_batch_eff",
    "bench_config",
    "bench_profile",
    "bench_serve",
    "bench_start",
    "bench_summary",
    "bench_surrogate",
    "checkpoint.resume",
    "checkpoint.save",
    "checkpoint.save_failed",
    "cluster_reject",
    "driver.interrupted",
    "driver.reexec",
    "driver.reexec_failed",
    "driver.retry",
    "flame",
    "fleet.action",
    "fleet.spawn_timeout",
    "flywheel.promoted",
    "flywheel.rejected",
    "flywheel.round",
    "health.signal",
    "odeint",
    "rescue",
    "schedule.adjust",
    "schedule.calibration",
    "schedule.compaction",
    "schedule.plan",
    "serve.batch",
    "serve.batch_error",
    "serve.close_timeout",
    "serve.demux_error",
    "serve.drain",
    "serve.rescue",
    "serve.transport.drain",
    "serve.worker_crashed",
    "solve",
    "staging.cache_corrupt",
    "staging.cache_error",
    "staging.failed",
    "supervisor.backend_lost",
    "supervisor.drain",
    "supervisor.drain_wait",
    "supervisor.kill_report",
    "supervisor.kill_report_failed",
    "supervisor.respawn_exhausted",
    "supervisor.spawn",
    "trace.span",
)

EVENT_PREFIXES = ()

# -- health signals ---------------------------------------------------------

#: canonical operator-signal names the :mod:`pychemkin_tpu.health`
#: rule engine may emit (the ``signal`` field of a ``health.signal``
#: event, and the ``name`` of every shipped rule dict). The lint's
#: ``telemetry-health-signals`` rule pins both the engine's exported
#: ``SIGNAL_NAMES`` tuple and every rule-dict ``"name"`` literal in
#: ``pychemkin_tpu/health/signals.py`` to this set, so a typo'd
#: signal name fails chemlint, not production dashboards.
HEALTH_SIGNALS = (
    "BACKEND_DOWN",
    "COMPILE_STORM",
    "DEADLINE_PRESSURE",
    "ERROR_BUDGET_BURN",
    "LADDER_SATURATED",
    "MEMBER_DEGRADED",
    "PREDICTOR_DECALIBRATED",
    "SURROGATE_RETRAIN",
)

#: field names a ``health.signal`` event carries beyond the spine's
#: ``t``/``kind`` — the contract between the rule engine and the
#: downstream readers (chemtop's alerts panel, the loadgen artifact's
#: signal timeline, flight-recorder correlation).
HEALTH_EVENT_FIELDS = (
    "signal",
    "severity",
    "state",
    "window_s",
    "evidence",
    "fired_at",
    "cleared_at",
    "member",
)

# -- program observatory ----------------------------------------------------

#: the counters :mod:`pychemkin_tpu.obs.programs` emits — every entry
#: must be derivable from :data:`COUNTERS` / :data:`COUNTER_PREFIXES`
#: and every counter the obs package increments must be derivable from
#: this tuple (the lint's ``telemetry-program-counters`` rule checks
#: both directions, mirroring ``SCHEDULE_COUNTERS``). The global is
#: always the sum of the per-program family.
PROGRAM_COUNTERS = (
    "program.compiles",
    "program.compiles.",
)

#: the trace-span field carrying the compiled-program identity on
#: ``serve.dispatch`` spans — the join key between wall-clock spans
#: and the obs registry's per-program cost attribution. The lint pins
#: the field to the actual ``emit_span`` call site in serve/server.py.
PROGRAM_SPAN_FIELD = "program_id"

# -- surrogate flywheel -----------------------------------------------------

#: the trace-span field carrying the serving surrogate's model
#: generation on ``serve.surrogate`` spans (stamped from the model's
#: ``meta["model_gen"]``) — the join key between a traced answer and
#: the flywheel promotion (``flywheel.promoted`` event) that installed
#: the model which produced it.
MODEL_GEN_SPAN_FIELD = "model_gen"

# -- timers (recorder.section blocks) ---------------------------------------

TIMERS = ()

TIMER_PREFIXES = ()

# -- trace spans ------------------------------------------------------------

SPANS = (
    "client.wire",
    "fleet.reroute",
    "rescue.rung",
    "serve.admission",
    "serve.batch_window",
    "serve.dispatch",
    "serve.expired",
    "serve.rescue_rung",
    "serve.surrogate",
    "supervisor.backend_lost",
    "supervisor.resubmit",
)

SPAN_PREFIXES = ()

__all__ = [
    "COUNTERS", "COUNTER_PREFIXES", "GAUGES", "GAUGE_PREFIXES",
    "HISTOGRAMS", "HISTOGRAM_PREFIXES", "EVENTS", "EVENT_PREFIXES",
    "HEALTH_SIGNALS", "HEALTH_EVENT_FIELDS",
    "PROGRAM_COUNTERS", "PROGRAM_SPAN_FIELD", "MODEL_GEN_SPAN_FIELD",
    "TIMERS", "TIMER_PREFIXES", "SPANS", "SPAN_PREFIXES",
]
