"""Structured metrics recorder: counters, gauges, device-fenced timers.

The observability core the bench and solver layers thread their
per-solve statistics through (n_steps / n_rejected / n_newton,
compile_s, per-stage wall time, LU residual-fallback counts). Three
surfaces:

- host counters/gauges/timers on :class:`MetricsRecorder`, with
  ``section(...)`` timing blocks fenced by ``jax.block_until_ready`` so
  a section charges DEVICE time, not Python dispatch time;
- structured events: ``event(kind, **fields)`` appends one JSONL line to
  the attached crash-safe sink (see :mod:`.sink`) and keeps a bounded
  in-memory tail for ``solve_report()``-style surfaces;
- a device→host counter bridge (:func:`device_increment`) for counts
  that only exist inside a jitted program (the pivot-free LU's
  stagnated-refinement flag): a ``jax.debug.callback`` increments the
  host counter when the program runs. The bridge is compiled in only
  when enabled at TRACE time (:func:`device_counters_enabled`), so the
  hot sweep path carries zero callback nodes unless asked for.

The module-level default recorder is what the ops/model layers use when
the caller does not pass one; ``configure(path)`` attaches a crash-safe
sink to it.
"""

from __future__ import annotations

import collections
import contextlib
import os
import time
from typing import Any, Dict, Optional

from .sink import JsonlSink, timestamp

#: environment switch for the device→host counter bridge; checked at
#: trace time so disabling it removes the callback nodes entirely
_DEVICE_COUNTERS_ENV = "PYCHEMKIN_TELEMETRY_DEVICE"


def device_counters_enabled() -> bool:
    """Whether jitted code should embed device→host counter callbacks
    (default on; export ``PYCHEMKIN_TELEMETRY_DEVICE=0`` to strip them
    from compiled programs)."""
    return os.environ.get(_DEVICE_COUNTERS_ENV, "1") != "0"


class MetricsRecorder:
    """Counters + gauges + device-fenced wall-clock timers + events."""

    def __init__(self, sink: Optional[JsonlSink] = None,
                 max_events: int = 256):
        self.counters: Dict[str, int] = collections.defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, float] = collections.defaultdict(float)
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        self._sink = sink

    # -- sink plumbing ---------------------------------------------------
    def attach_sink(self, sink: Optional[JsonlSink]) -> None:
        self._sink = sink

    @property
    def sink(self) -> Optional[JsonlSink]:
        return self._sink

    # -- scalars ---------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        self.counters[name] += int(n)

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    @contextlib.contextmanager
    def section(self, name: str, fence: Any = None):
        """Time a block into ``timers[name]``. ``fence`` (an array, tree,
        or list the block appends device arrays to) is blocked on before
        the clock stops, so asynchronous dispatch cannot hide device
        time."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None and any(
                    True for _ in _iter_leaves(fence)):
                import jax

                jax.block_until_ready(fence)
            self.timers[name] += time.perf_counter() - t0

    # -- events ----------------------------------------------------------
    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Record one structured event; appended to the sink (if any) as
        a crash-safe JSONL line and kept in the in-memory tail."""
        ev = {"t": timestamp(), "kind": kind}
        ev.update(fields)
        self._events.append(ev)
        if self._sink is not None:
            self._sink.emit(ev)
        return ev

    def last_event(self, kind: str) -> Optional[Dict[str, Any]]:
        for ev in reversed(self._events):
            if ev["kind"] == kind:
                return ev
        return None

    def events(self, kind: Optional[str] = None):
        return [ev for ev in self._events
                if kind is None or ev["kind"] == kind]

    # -- aggregate views -------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Aggregate state as one JSON-ready dict; also rewritten
        atomically to the sink's snapshot file when a sink is attached."""
        snap = {
            "t": timestamp(),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "timers": {k: round(v, 6) for k, v in self.timers.items()},
        }
        if self._sink is not None:
            self._sink.write_snapshot(snap)
        return snap

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.timers.clear()
        self._events.clear()


def _iter_leaves(x):
    if isinstance(x, (list, tuple)):
        for item in x:
            yield from _iter_leaves(item)
    elif x is not None:
        yield x


#: process-wide default recorder (ops/model layers fall back to this)
_default = MetricsRecorder()


def get_recorder() -> MetricsRecorder:
    return _default


def configure(path: Optional[str] = None,
              snapshot_path: Optional[str] = None) -> MetricsRecorder:
    """Attach a crash-safe JSONL sink at ``path`` to the default
    recorder (or detach with ``path=None``)."""
    old = _default.sink
    if old is not None:
        old.close()
    _default.attach_sink(
        JsonlSink(path, snapshot_path) if path is not None else None)
    return _default


def record_event(kind: str, **fields: Any) -> Dict[str, Any]:
    return _default.event(kind, **fields)


def device_increment(name: str, value) -> None:
    """Increment a host counter from inside a jitted program.

    Embeds a ``jax.debug.callback`` that adds ``value`` (a traced
    integer/bool scalar; bools count as 1) to the default recorder's
    counter when the compiled program executes. No-op — zero graph
    nodes — when device counters are disabled at trace time, so hot
    paths pay nothing unless observability is on.
    """
    if not device_counters_enabled():
        return
    import jax
    import jax.numpy as jnp

    def _cb(v):
        _default.inc(name, int(v))

    jax.debug.callback(_cb, jnp.sum(jnp.asarray(value, jnp.int32)))
