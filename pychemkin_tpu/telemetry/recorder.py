"""Structured metrics recorder: counters, gauges, device-fenced timers.

The observability core the bench and solver layers thread their
per-solve statistics through (n_steps / n_rejected / n_newton,
compile_s, per-stage wall time, LU residual-fallback counts). Three
surfaces:

- host counters/gauges/timers on :class:`MetricsRecorder`, with
  ``section(...)`` timing blocks fenced by ``jax.block_until_ready`` so
  a section charges DEVICE time, not Python dispatch time, and
  log-spaced-bucket :class:`Histogram` distributions via
  ``observe(name, value)`` (the serving layer's latency/occupancy
  primitive — p50/p95/p99 summarized in ``snapshot()``);
- structured events: ``event(kind, **fields)`` appends one JSONL line to
  the attached crash-safe sink (see :mod:`.sink`) and keeps a bounded
  in-memory tail for ``solve_report()``-style surfaces;
- a device→host counter bridge (:func:`device_increment`) for counts
  that only exist inside a jitted program (the pivot-free LU's
  stagnated-refinement flag): a ``jax.debug.callback`` increments the
  host counter when the program runs. The bridge is compiled in only
  when enabled at TRACE time (:func:`device_counters_enabled`), so the
  hot sweep path carries zero callback nodes unless asked for.

The module-level default recorder is what the ops/model layers use when
the caller does not pass one; ``configure(path)`` attaches a crash-safe
sink to it.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import knobs
from .sink import JsonlSink, atomic_write_json, timestamp

#: environment switch for the device→host counter bridge; checked at
#: trace time so disabling it removes the callback nodes entirely
_DEVICE_COUNTERS_ENV = "PYCHEMKIN_TELEMETRY_DEVICE"

#: ring-buffer cap for the in-memory event tail (see
#: :class:`MetricsRecorder`): a long chaos soak emits events without
#: bound, and the JSONL sink is the full record — memory only needs the
#: recent tail a flight-recorder dump or ``last_event`` lookup wants
EVENTS_CAP_ENV = "PYCHEMKIN_TELEMETRY_EVENTS_CAP"
DEFAULT_EVENTS_CAP = 4096


def _events_cap() -> int:
    # registry parse: int, floor 1, unparseable falls back to the
    # default — a garbage cap must not take down a serving process
    return knobs.value(EVENTS_CAP_ENV)


def device_counters_enabled() -> bool:
    """Whether jitted code should embed device→host counter callbacks
    (default on; export ``PYCHEMKIN_TELEMETRY_DEVICE=0`` to strip them
    from compiled programs)."""
    return knobs.value(_DEVICE_COUNTERS_ENV)


#: histogram bucket edges: log-spaced, 8 buckets per decade over
#: [1e-6, 1e9) — wide enough for latencies in ms OR s, occupancies,
#: queue depths. Values outside the range land in the open end buckets.
_HIST_EDGES: List[float] = [10.0 ** (k / 8.0) for k in range(-48, 73)]


class Histogram:
    """Log-spaced-bucket value distribution with exact count/sum/min/max
    and interpolated percentiles.

    The latency primitive of the serving layer: ``observe(value)`` is
    O(log n_buckets) and allocation-free, so the request hot path can
    afford one per request; ``summary()`` reduces the buckets to the
    JSON-ready ``{count, sum, mean, min, max, p50, p95, p99}`` shape
    that ``MetricsRecorder.snapshot()`` publishes. Percentiles are
    estimated by log-linear interpolation inside the winning bucket and
    clamped to the exact observed [min, max], so a single-value
    histogram reports that value for every percentile."""

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = collections.defaultdict(int)  # edge index -> n
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.counts[bisect.bisect_right(_HIST_EDGES, v)] += 1

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile (q in [0, 100])."""
        if self.count == 0:
            return math.nan
        rank = (q / 100.0) * self.count
        seen = 0
        for idx in sorted(self.counts):
            n_here = self.counts[idx]
            seen += n_here
            if seen >= rank:
                lo = _HIST_EDGES[idx - 1] if idx > 0 else self.min
                hi = (_HIST_EDGES[idx] if idx < len(_HIST_EDGES)
                      else self.max)
                # interpolate by the rank's position INSIDE the winning
                # bucket (log-space when possible), so two percentiles
                # landing in one bucket still order correctly
                frac = (rank - (seen - n_here)) / n_here
                if lo > 0 and hi > lo:
                    est = lo * (hi / lo) ** frac
                else:
                    est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
        return self.max

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / self.count, 6),
            "min": round(self.min, 6),
            "max": round(self.max, 6),
            "p50": round(self.percentile(50.0), 6),
            "p95": round(self.percentile(95.0), 6),
            "p99": round(self.percentile(99.0), 6),
        }

    # -- mergeable wire form --------------------------------------------
    def state(self) -> Dict[str, Any]:
        """JSON-ready raw state (bucket counts keyed by edge index as
        strings, exact count/sum/min/max). Unlike :meth:`summary`,
        states MERGE exactly: two processes' histograms over the same
        fixed edge set combine bucket-wise, so fleet percentiles are
        computed from the merged distribution, not averaged from
        per-process percentiles (which is statistically meaningless).
        This is what the transport ``metrics`` op ships and what
        ``chemtop`` merges across backends."""
        return {"counts": {str(k): v for k, v in self.counts.items()},
                "count": self.count, "sum": round(self.sum, 6),
                "min": round(self.min, 6) if self.count else None,
                "max": round(self.max, 6) if self.count else None}

    def merge_state(self, state: Optional[Dict[str, Any]]) -> "Histogram":
        """Fold one :meth:`state` dict in (empty/None states are
        no-ops); returns self for chaining."""
        if not state or not state.get("count"):
            return self
        for k, v in (state.get("counts") or {}).items():
            self.counts[int(k)] += int(v)
        self.count += int(state["count"])
        self.sum += float(state.get("sum") or 0.0)
        if state.get("min") is not None:
            self.min = min(self.min, float(state["min"]))
        if state.get("max") is not None:
            self.max = max(self.max, float(state["max"]))
        return self

    @classmethod
    def from_states(cls, states) -> "Histogram":
        h = cls()
        for s in states:
            h.merge_state(s)
        return h


def merge_histogram_states(states) -> Dict[str, float]:
    """Merge raw histogram states (see :meth:`Histogram.state`) from
    several processes into ONE summary — the fleet-level
    count/sum/mean/min/max/p50/p95/p99. Empty states contribute
    nothing; disjoint bucket sets union; shared buckets add."""
    return Histogram.from_states(states).summary()


class HistogramSubtractionError(ValueError):
    """``subtract_histogram_states(a, b)`` was asked for a windowed
    difference where ``b`` is NOT a prefix of ``a`` — some bucket (or
    the total count) would go negative. Counters only ever grow inside
    one process generation, so a non-monotone pair means the emitting
    process respawned between the two scrapes; the caller must treat
    the window as reset, not trust a negative distribution."""


def _empty_state() -> Dict[str, Any]:
    return {"counts": {}, "count": 0, "sum": 0.0,
            "min": None, "max": None}


def subtract_histogram_states(a: Optional[Dict[str, Any]],
                              b: Optional[Dict[str, Any]]
                              ) -> Dict[str, Any]:
    """The inverse of :func:`merge_histogram_states` on RAW states:
    ``a - b`` where ``b`` is an earlier scrape of the same
    still-growing histogram. The result is itself a mergeable state
    describing exactly the observations made BETWEEN the two scrapes —
    what windowed (last-N-seconds) percentiles are computed from,
    instead of since-boot distributions.

    Non-negative by construction: any bucket of ``b`` exceeding its
    bucket in ``a`` (or a count/bucket-total mismatch) raises the
    typed :class:`HistogramSubtractionError` — that shape means the
    emitting process restarted between scrapes.

    Exact min/max of the in-window observations are unknowable from
    bucket counts alone, so the result carries CONSERVATIVE bounds
    derived from the surviving buckets' edges (clamped by ``a``'s
    exact bounds) — within one bucket boundary of the truth, which is
    also the resolution of every percentile estimate. ``b`` empty
    returns ``a`` unchanged (exact bounds)."""
    a = a if a and a.get("count") else _empty_state()
    b = b if b and b.get("count") else _empty_state()
    a_counts = {int(k): int(v) for k, v in
                (a.get("counts") or {}).items() if int(v)}
    b_counts = {int(k): int(v) for k, v in
                (b.get("counts") or {}).items() if int(v)}
    if not b_counts and not b.get("count"):
        # exact fast path: nothing to remove, a's bounds are exact
        return {"counts": {str(k): v for k, v in a_counts.items()},
                "count": int(a.get("count") or 0),
                "sum": round(float(a.get("sum") or 0.0), 6),
                "min": a.get("min"), "max": a.get("max")}
    diff: Dict[int, int] = {}
    for k, bv in b_counts.items():
        av = a_counts.get(k, 0)
        if bv > av:
            raise HistogramSubtractionError(
                f"bucket {k}: subtrahend has {bv} > minuend {av} — "
                "the emitting process restarted between scrapes")
    for k, av in a_counts.items():
        d = av - b_counts.get(k, 0)
        if d:
            diff[k] = d
    count = int(a.get("count") or 0) - int(b.get("count") or 0)
    if count < 0 or count != sum(diff.values()):
        raise HistogramSubtractionError(
            f"count delta {count} does not match bucket delta "
            f"{sum(diff.values())} — inconsistent states (restart?)")
    if count == 0:
        return _empty_state()
    total = float(a.get("sum") or 0.0) - float(b.get("sum") or 0.0)
    # conservative bounds from the surviving buckets: bucket k holds
    # values in [edge[k-1], edge[k]); a's exact global bounds still
    # bound every in-window value, so clamp by them
    lo_idx, hi_idx = min(diff), max(diff)
    lo = _HIST_EDGES[lo_idx - 1] if lo_idx > 0 else -math.inf
    hi = _HIST_EDGES[hi_idx] if hi_idx < len(_HIST_EDGES) else math.inf
    if a.get("min") is not None:
        lo = max(lo, float(a["min"]))
    if a.get("max") is not None:
        hi = min(hi, float(a["max"]))
    if not math.isfinite(lo):
        lo = hi if math.isfinite(hi) else 0.0
    if not math.isfinite(hi):
        hi = lo
    return {"counts": {str(k): v for k, v in diff.items()},
            "count": count, "sum": round(total, 6),
            "min": round(lo, 6), "max": round(hi, 6)}


class MetricsRecorder:
    """Counters + gauges + histograms + device-fenced wall-clock timers
    + events.

    Mutations are guarded by one internal lock: the serving layer
    increments counters and observes histograms from submitter, worker,
    and rescue threads concurrently, and a monitoring thread may call
    :meth:`snapshot` mid-traffic — unsynchronized ``dict[k] += n`` would
    drop updates and a dict resized during snapshot iteration would
    raise."""

    def __init__(self, sink: Optional[JsonlSink] = None,
                 max_events: Optional[int] = None):
        self.counters: Dict[str, int] = collections.defaultdict(
            int)                         # guarded-by: _lock
        self.gauges: Dict[str, float] = {}  # guarded-by: _lock
        self.timers: Dict[str, float] = collections.defaultdict(
            float)                       # guarded-by: _lock
        self.histograms: Dict[str, Histogram] = {}  # guarded-by: _lock
        # bounded ring: the tail a flight-recorder dump wants, not the
        # full record (that is the JSONL sink's job) — a long
        # --transport --chaos soak must not grow backend memory with
        # every event. Cap via PYCHEMKIN_TELEMETRY_EVENTS_CAP.
        self._events: collections.deque = collections.deque(
            maxlen=(_events_cap() if max_events is None
                    else max_events))    # guarded-by: _event_lock
        self._lock = threading.Lock()
        # events get their own lock: emit() does sink disk I/O, and
        # holding the metrics lock across a write/flush would stall
        # every hot-path inc()/observe() behind the filesystem
        self._event_lock = threading.Lock()
        self._sink = sink

    # -- sink plumbing ---------------------------------------------------
    def attach_sink(self, sink: Optional[JsonlSink]) -> None:
        self._sink = sink

    @property
    def sink(self) -> Optional[JsonlSink]:
        return self._sink

    # -- scalars ---------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += int(n)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named histogram (created on first
        use). Summaries (count/sum/mean/min/max/p50/p95/p99) appear
        under ``histograms`` in :meth:`snapshot`."""
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram()
            hist.observe(value)

    def histogram_summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            hist = self.histograms.get(name)
            return hist.summary() if hist is not None else {"count": 0}

    @contextlib.contextmanager
    def section(self, name: str, fence: Any = None):
        """Time a block into ``timers[name]``. ``fence`` (an array, tree,
        or list the block appends device arrays to) is blocked on before
        the clock stops, so asynchronous dispatch cannot hide device
        time."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None and any(
                    True for _ in _iter_leaves(fence)):
                import jax

                jax.block_until_ready(fence)
            with self._lock:
                self.timers[name] += time.perf_counter() - t0

    # -- events ----------------------------------------------------------
    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Record one structured event; appended to the sink (if any) as
        a crash-safe JSONL line and kept in the in-memory tail."""
        ev = {"t": timestamp(), "kind": kind}
        ev.update(fields)
        # sink emit under the event lock: worker/rescue/caller threads
        # all emit, and interleaved writes on one line-buffered text
        # file would tear JSONL lines mid-log (read_jsonl only
        # tolerates a torn FINAL line)
        with self._event_lock:
            self._events.append(ev)
            if self._sink is not None:
                self._sink.emit(ev)
        return ev

    def last_event(self, kind: str) -> Optional[Dict[str, Any]]:
        """Most recent event of ``kind`` still in the RECENT TAIL (the
        bounded ring; None once it aged out — the JSONL sink is the
        full record)."""
        with self._event_lock:
            for ev in reversed(self._events):
                if ev["kind"] == kind:
                    return ev
        return None

    def events(self, kind: Optional[str] = None):
        """The RECENT TAIL of events (bounded ring, cap
        ``PYCHEMKIN_TELEMETRY_EVENTS_CAP``), oldest first — NOT the
        full history; read the JSONL sink for that."""
        with self._event_lock:
            return [ev for ev in self._events
                    if kind is None or ev["kind"] == kind]

    # -- aggregate views -------------------------------------------------
    def histogram_states(self) -> Dict[str, Dict[str, Any]]:
        """Raw (mergeable) histogram states — what the fleet ``metrics``
        op ships so ``chemtop`` can merge distributions exactly across
        backends (see :meth:`Histogram.state`)."""
        with self._lock:
            return {k: h.state() for k, h in self.histograms.items()}

    def snapshot(self, write: bool = True) -> Dict[str, Any]:
        """Aggregate state as one JSON-ready dict; also rewritten
        atomically to the sink's snapshot file when a sink is attached.
        ``write=False`` skips that disk write (and its event-lock
        hold): the read-only form for periodic scrapers — a metrics
        poll must not stall hot-path event emission behind file I/O."""
        with self._lock:
            snap = {
                "t": timestamp(),
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timers": {k: round(v, 6)
                           for k, v in self.timers.items()},
                "histograms": {k: h.summary()
                               for k, h in self.histograms.items()},
            }
        if write and self._sink is not None:
            # under the sink-I/O lock: concurrent snapshots must not
            # interleave their last-writer-wins renames out of order
            with self._event_lock:
                self._sink.write_snapshot(snap)
        return snap

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.timers.clear()
            self.histograms.clear()
        with self._event_lock:
            self._events.clear()


def _iter_leaves(x):
    if isinstance(x, (list, tuple)):
        for item in x:
            yield from _iter_leaves(item)
    elif x is not None:
        yield x


#: process-wide default recorder (ops/model layers fall back to this)
_default = MetricsRecorder()


def get_recorder() -> MetricsRecorder:
    return _default


def configure(path: Optional[str] = None,
              snapshot_path: Optional[str] = None) -> MetricsRecorder:
    """Attach a crash-safe JSONL sink at ``path`` to the default
    recorder (or detach with ``path=None``)."""
    old = _default.sink
    if old is not None:
        old.close()
    _default.attach_sink(
        JsonlSink(path, snapshot_path) if path is not None else None)
    return _default


def record_event(kind: str, **fields: Any) -> Dict[str, Any]:
    return _default.event(kind, **fields)


#: flight-recorder dump destinations: an exact file path, or a
#: directory (file named flight_<pid>.json — respawned backend
#: generations are different pids, so each death keeps its own dump)
FLIGHT_PATH_ENV = "PYCHEMKIN_FLIGHT_PATH"
FLIGHT_DIR_ENV = "PYCHEMKIN_FLIGHT_DIR"


def flight_recorder_path() -> Optional[str]:
    """Where a flight dump would land, or None when disabled (neither
    env var set and no explicit path given)."""
    path = knobs.value(FLIGHT_PATH_ENV)
    if path:
        return path
    d = knobs.value(FLIGHT_DIR_ENV)
    if d:
        return os.path.join(d, f"flight_{os.getpid()}.json")
    return None


def flight_recorder_dump(reason: str, recorder: Optional[MetricsRecorder]
                         = None, path: Optional[str] = None,
                         **fields: Any) -> Optional[str]:
    """Dump the recorder's recent-event ring + aggregate counters as a
    post-mortem artifact (atomic rewrite; crash-safe by construction).

    This is the catchable-death half of the crash flight recorder: a
    backend wires it to SIGTERM/atexit so a drain, a poison-triggered
    exit, or any orderly death leaves its last ``EVENTS_CAP`` events on
    disk. SIGKILL-class deaths cannot run this — for those the
    SUPERVISOR writes a kill report from the outside (see
    :meth:`pychemkin_tpu.serve.supervisor.Supervisor`). Returns the
    path written, or None when no destination is configured."""
    rec = recorder if recorder is not None else _default
    path = path or flight_recorder_path()
    if path is None:
        return None
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with rec._lock:
        aggregates = {
            "counters": dict(rec.counters),
            "gauges": dict(rec.gauges),
            "histograms": {k: h.summary()
                           for k, h in rec.histograms.items()},
        }
    atomic_write_json(path, {
        "t": timestamp(), "reason": reason, "pid": os.getpid(),
        **fields, **aggregates, "events": rec.events()})
    return path


def device_increment(name: str, value) -> None:
    """Increment a host counter from inside a jitted program.

    Embeds a ``jax.debug.callback`` that adds ``value`` (a traced
    integer/bool scalar; bools count as 1) to the default recorder's
    counter when the compiled program executes. No-op — zero graph
    nodes — when device counters are disabled at trace time, so hot
    paths pay nothing unless observability is on.
    """
    if not device_counters_enabled():
        return
    import jax
    import jax.numpy as jnp

    def _cb(v):
        _default.inc(name, int(v))

    jax.debug.callback(_cb, jnp.sum(jnp.asarray(value, jnp.int32)))
