"""Program observatory: per-compiled-program cost attribution.

Every jit entry point the repo dispatches (serve engine buckets,
compaction ladder rungs, surrogate predict) registers a stable
``program_id`` — a hash of (mechanism signature, kind, shape, resolved
knob config) — and banks its dispatches into the existing telemetry
surfaces:

- ``program.compiles`` / ``program.compiles.<id>`` counters (compile
  events, classified persistent-XLA-cache warm vs cold when the jax
  monitoring hook is available);
- ``program.wall_ms.<id>`` histograms (per-dispatch wall, mergeable
  fleet-wide by histogram-state summation);
- a per-process registry (:func:`get_registry`) carrying the program
  metadata, model-FLOP totals from the analytic cost model
  (:mod:`pychemkin_tpu.mechanism.costmodel`), and first-compile wall.

``chemtop`` merges the per-backend ``programs`` metrics blocks into a
fleet panel reporting wall share, achieved GFLOP/s, and ``mfu_pct``
against the calibrated GEMM roof; the health engine's
``COMPILE_STORM`` signal and ``run_suite --compile-audit`` consume the
compile counters as the "zero new compiles after warmup" guard.
"""

from __future__ import annotations

from .programs import (ProgramRegistry, cache_hits, cache_listener_available,
                       get_registry, mech_signature, program_id,
                       reset_registry)

__all__ = [
    "ProgramRegistry", "cache_hits", "cache_listener_available",
    "get_registry", "mech_signature", "program_id", "reset_registry",
]
