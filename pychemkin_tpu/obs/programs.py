"""Compiled-program registry: identity, compile events, dispatch cost.

A *program* is one compiled XLA executable the repo can dispatch: a
serve engine's jitted batch fn at one bucket shape, one compaction
ladder rung of an ignition sweep kernel, a surrogate ensemble predict.
Its identity — :func:`program_id` — hashes everything that keys the
jit cache entry (mechanism signature, kind, shape, resolved knob
config) and nothing about the process that compiled it, so the same
logical program gets the same id across respawns and across the fleet
(the join key chemtop merges on).

The registry is deliberately dumb: pure-python bookkeeping plus
counter/histogram emission through the normal recorder, so everything
downstream (fleet merge, windowed health deltas, the compile-audit
gate) rides machinery that already exists. Wall time lives in
``program.wall_ms.<id>`` histograms — their states sum EXACTLY under
fleet merge, so per-program wall shares are computed from summed
states, never averaged percentages. Model FLOPs accumulate in the
registry and ship in the ``programs`` metrics block.

This module must stay importable without jax (chemtop-side tests
import it for :func:`program_id`); the persistent-compile-cache
listener imports jax lazily and degrades to "unknown" classification
when the internal monitoring hook is absent.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, Optional, Tuple

from .. import telemetry

#: length of the truncated sha256 hex id — 48 bits, far beyond any
#: plausible fleet's distinct-program count
_ID_LEN = 12


def program_id(mech_sig: str, kind: str, shape: Tuple[int, ...],
               config: Dict[str, Any]) -> str:
    """Stable identity of one compiled program: sha256 over a canonical
    JSON encoding of (mechanism signature, kind, shape, sorted resolved
    config), truncated to 12 hex chars. Pure function of its arguments
    — stable across process respawn by construction, different under
    any knob/mech/shape perturbation because those ARE the payload."""
    payload = json.dumps(
        {"mech": str(mech_sig), "kind": str(kind),
         "shape": [int(s) for s in shape],
         "config": {str(k): config[k] for k in sorted(config)}},
        sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:_ID_LEN]


# -- persistent compile-cache classification --------------------------------

#: monotone count of persistent-XLA-cache hit events observed by the
#: (lazily installed) jax monitoring listener; ``available`` stays
#: False when the internal hook is missing (classification "unknown")
_CACHE_EVENTS = {"n": 0, "installed": False, "available": False}
_CACHE_LOCK = threading.Lock()


def _install_cache_listener() -> None:
    with _CACHE_LOCK:
        if _CACHE_EVENTS["installed"]:
            return
        _CACHE_EVENTS["installed"] = True
        try:
            # jax 0.4.x internal hook: every persistent-compilation-
            # cache hit records a '/jax/compilation_cache/cache_hits'
            # event through jax._src.monitoring. Internal API —
            # any import/signature drift degrades to "unknown".
            from jax._src import monitoring

            def _on_event(event: str, **kw: Any) -> None:
                if "cache_hit" in event:
                    with _CACHE_LOCK:
                        _CACHE_EVENTS["n"] += 1

            monitoring.register_event_listener(_on_event)
            _CACHE_EVENTS["available"] = True
        except Exception:
            _CACHE_EVENTS["available"] = False


def cache_hits() -> int:
    """Persistent-cache hit events seen so far (installs the listener
    on first call); -1 when the monitoring hook is unavailable. Sample
    before/after a compiling dispatch and pass the delta to
    :meth:`ProgramRegistry.record_dispatch` to classify warm vs cold."""
    _install_cache_listener()
    with _CACHE_LOCK:
        return _CACHE_EVENTS["n"] if _CACHE_EVENTS["available"] else -1


def cache_listener_available() -> bool:
    _install_cache_listener()
    return bool(_CACHE_EVENTS["available"])


# -- mechanism signature memo -----------------------------------------------

#: id(record) -> signature memo; the staged kernel's sig is preferred
#: (already computed at parse time), else one checkpoint.signature
#: pass per distinct record object
_SIG_MEMO: Dict[int, str] = {}
_SIG_LOCK = threading.Lock()


def mech_signature(mech) -> str:
    """The record's mechanism signature for program identity: the
    staged kernel's parse-time sig when present, else computed once
    per record object (memoized by ``id`` — records are immutable in
    practice and the memo is advisory identity, not correctness)."""
    stage = getattr(mech, "rop_stage", None)
    if stage is not None and getattr(stage, "sig", None):
        return str(stage.sig)
    key = id(mech)
    with _SIG_LOCK:
        sig = _SIG_MEMO.get(key)
    if sig is None:
        from ..mechanism.staging import mechanism_signature
        sig = mechanism_signature(mech)
        with _SIG_LOCK:
            _SIG_MEMO[key] = sig
    return sig


# -- the registry -----------------------------------------------------------

class ProgramRegistry:
    """Per-process program bookkeeping, thread-safe under one lock.

    ``register`` is idempotent; ``record_dispatch`` banks one dispatch
    of a registered program: compile events increment the
    ``program.compiles`` counters (global = sum of the per-id family)
    and store first-compile wall + warm/cold classification; every
    accounted dispatch observes its wall into the program's
    ``program.wall_ms.<id>`` histogram and accumulates model GFLOPs.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: Dict[str, Dict[str, Any]] = {}

    def register(self, pid: str, *, kind: str, mech_sig: str,
                 shape: Tuple[int, ...], config: Dict[str, Any]) -> str:
        with self._lock:
            if pid not in self._programs:
                self._programs[pid] = {
                    "kind": str(kind),
                    "mech_sig": str(mech_sig)[:12],
                    "shape": [int(s) for s in shape],
                    "config": {str(k): _jsonable(v)
                               for k, v in sorted(config.items())},
                    "compiles": 0,
                    "dispatches": 0,
                    "model_gflop_sum": 0.0,
                    "first_compile_ms": None,
                    "cache_source": None,
                }
        return pid

    def dispatches(self, pid: str) -> int:
        with self._lock:
            p = self._programs.get(pid)
            return int(p["dispatches"]) if p else 0

    def record_dispatch(self, pid: str, wall_ms: float, *,
                        model_gflop: Optional[float] = None,
                        compiled: bool = False,
                        cache_hits_delta: Optional[int] = None,
                        recorder=None,
                        accounted: bool = True) -> None:
        """Bank one dispatch. ``compiled`` dispatches count into the
        compile counters and store first-compile wall / warm-vs-cold
        (``cache_hits_delta`` > 0 means the executable came from the
        persistent cache — a warm compile; 0 means a real trace+build;
        None/negative means unclassifiable). ``accounted=False``
        (warmup) skips the wall histogram and model-FLOP accumulation
        so warm-up dummies never pollute the cost attribution, while
        compile events still land — warmup compiles ARE the expected
        cold/warm population the audit baselines against."""
        rec = recorder if recorder is not None else telemetry.get_recorder()
        with self._lock:
            p = self._programs.get(pid)
            if p is None:    # defensive: dispatch before register
                return
            if compiled:
                p["compiles"] += 1
                if p["first_compile_ms"] is None:
                    p["first_compile_ms"] = round(float(wall_ms), 3)
                    if cache_hits_delta is None or cache_hits_delta < 0:
                        p["cache_source"] = "unknown"
                    elif cache_hits_delta > 0:
                        p["cache_source"] = "warm"
                    else:
                        p["cache_source"] = "cold"
            if accounted:
                p["dispatches"] += 1
                if model_gflop is not None:
                    p["model_gflop_sum"] += float(model_gflop)
        if compiled:
            rec.inc("program.compiles")
            rec.inc(f"program.compiles.{pid}")
        if accounted:
            rec.observe(f"program.wall_ms.{pid}", float(wall_ms))

    def add_model_gflop(self, pid: str, gflop: float) -> None:
        """Late model-FLOP attribution (a sweep splits its total across
        the rungs it actually ran, proportional to rung wall)."""
        with self._lock:
            p = self._programs.get(pid)
            if p is not None:
                p["model_gflop_sum"] += float(gflop)

    def programs_state(self) -> Dict[str, Any]:
        """JSON-ready registry state for the metrics reply: per-id
        metadata + compile/dispatch/model-FLOP tallies (wall ships
        separately as ``program.wall_ms.<id>`` histogram states)."""
        with self._lock:
            by_id = {pid: dict(p) for pid, p in self._programs.items()}
        return {"by_id": by_id,
                "cache_listener": bool(_CACHE_EVENTS["available"])}

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return str(v)


_REGISTRY: Optional[ProgramRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> ProgramRegistry:
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = ProgramRegistry()
        return _REGISTRY


def reset_registry() -> None:
    """Fresh registry (tests; a forked backend startup)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = ProgramRegistry()
