"""Per-condition cost prediction: how the scheduler guesses which
elements are expensive BEFORE paying for a solve.

The default predictor is a mechanism-timescale estimate: a Gershgorin
row bound on the analytic RHS Jacobian at the initial state
(:func:`pychemkin_tpu.ops.jacobian.batch_rhs_jacobian` assembles it in
closed form — two skinny matmuls, one evaluation per condition, vs the
thousands a stiff solve performs). The bound caps the spectral radius
of J, i.e. the fastest chemical timescale 1/|lambda_max|; multiplied
by the integration horizon it is a dimensionless stiffness ratio — an
upper proxy for how many stiff steps the controller will take. The
ORDERING is what the scheduler consumes (cohorts form from ranks, not
absolute costs), so a monotone-correlated proxy is enough.

The served surrogate ensemble (PR 9) is an optional sharper predictor:
it prices ignition delay in ~0.07 ms, and a later-igniting condition
spends longer in the small-step induction window — pass the model to
:func:`surrogate_cost_predictor` and hand the result to the sweep's
``cost_fn``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..ops import jacobian, odeint, reactors

#: jitted predictor programs keyed by (mech identity, problem, energy)
_COST_CACHE: Dict[Tuple, Any] = {}


def _cost_fn(mech, problem: str, energy: str):
    key = (id(mech), problem, energy)
    fn = _COST_CACHE.get(key)
    if fn is None:
        jac_fn = jacobian.batch_rhs_jacobian(problem, energy)

        def one(T0, P0, Y0, t_end):
            args, y0, _ = reactors.sweep_lane_args(mech, problem, T0,
                                                   P0, Y0)
            J = jac_fn(jnp.zeros((), dtype=y0.dtype), y0, args)
            # Gershgorin: max over rows of sum_j |J_ij| bounds the
            # spectral radius — the fastest timescale's rate (shared
            # with the solve profile's harvest-time sample)
            rate = odeint.gershgorin_rate(J)
            return rate * t_end

        fn = _COST_CACHE[key] = jax.jit(jax.vmap(one))
    return fn


def stiffness_costs(mech, problem: str, energy: str, T0s, P0s, Y0s,
                    t_ends) -> np.ndarray:
    """Predicted relative cost [B] of each sweep condition: Gershgorin
    spectral-radius bound of the analytic Jacobian at t=0, times the
    integration horizon. All inputs broadcast along the batch axis
    exactly like :func:`~pychemkin_tpu.ops.reactors
    .ignition_delay_sweep`."""
    T0s = np.atleast_1d(np.asarray(T0s, np.float64))
    B = T0s.shape[0]
    P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
    Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                          (B, np.asarray(Y0s).shape[-1]))
    t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
    costs = _cost_fn(mech, problem, energy)(
        jnp.asarray(T0s), jnp.asarray(P0s), jnp.asarray(Y0s),
        jnp.asarray(t_ends))
    return np.asarray(costs, np.float64)


def spearman(a, b) -> Optional[float]:
    """Spearman rank correlation of two 1-D arrays over their jointly
    finite entries (pure numpy — average ranks for ties). None when
    fewer than 3 finite pairs remain or either side is constant (rank
    correlation is undefined there, and the gauge must say "no
    signal", not fake a number)."""
    a = np.asarray(a, np.float64).reshape(-1)
    b = np.asarray(b, np.float64).reshape(-1)
    if a.shape != b.shape:
        raise ValueError(f"spearman needs same-shape arrays, got "
                         f"{a.shape} vs {b.shape}")
    m = np.isfinite(a) & np.isfinite(b)
    a, b = a[m], b[m]
    if a.size < 3:
        return None

    def ranks(x):
        order = np.argsort(x, kind="stable")
        r = np.empty(x.size, np.float64)
        r[order] = np.arange(1, x.size + 1, dtype=np.float64)
        # average ranks over ties so tied predictions don't pick up
        # spurious (dis)agreement from sort order — O(n log n): mean
        # ordinal rank per distinct value, scattered back
        _, inv, counts = np.unique(x, return_inverse=True,
                                   return_counts=True)
        return (np.bincount(inv, weights=r) / counts)[inv]

    ra, rb = ranks(a), ranks(b)
    sa, sb = ra.std(), rb.std()
    if sa == 0.0 or sb == 0.0:
        return None
    return float(np.mean((ra - ra.mean()) * (rb - rb.mean()))
                 / (sa * sb))


def bank_predictor_calibration(costs, measured, *, recorder=None,
                               label: str = "",
                               job_report: Optional[dict] = None
                               ) -> Optional[float]:
    """Bank one sweep's predicted-vs-measured cost rank correlation —
    the LIVE calibration signal behind the scheduler's cost model
    (PR-11's one-off offline spearman numbers, now monitored
    continuously). ``costs`` are the predictor's per-element values,
    ``measured`` the realized per-element step attempts (NaN where a
    resumed-from-checkpoint chunk never executed this process).

    Emits the ``schedule.predictor_corr`` gauge (only when a
    correlation exists — a sweep too small to rank must not overwrite
    a real reading with null) and a ``schedule.calibration`` event
    either way, and mirrors the number into ``job_report`` — the
    operator-facing signal for when to switch ``cost_fn`` to the
    surrogate predictor. Returns the correlation (None = no
    signal)."""
    corr = spearman(costs, measured)
    rec = recorder if recorder is not None else telemetry.get_recorder()
    n_measured = int(np.count_nonzero(
        np.isfinite(np.asarray(measured, np.float64))))
    if corr is not None:
        rec.gauge("schedule.predictor_corr", round(corr, 4))
    rec.event("schedule.calibration", label=label,
              n=int(np.asarray(costs).size), n_measured=n_measured,
              predictor_corr=(round(corr, 4) if corr is not None
                              else None))
    if job_report is not None:
        job_report["predictor_corr"] = (round(corr, 4)
                                        if corr is not None else None)
    return corr


def surrogate_cost_predictor(model) -> Callable:
    """A sharper cost predictor from a trained ignition-delay
    surrogate (:mod:`pychemkin_tpu.surrogate`): predicted ignition
    delay, clamped to the horizon. A later-igniting condition holds
    the controller in its small-step induction window longer, so
    predicted delay orders integration cost. Returns a callable with
    the :func:`stiffness_costs` signature (mech/problem/energy are
    accepted and ignored — the model already encodes the chemistry).
    """
    from ..surrogate import model as sg_model

    def predict(mech, problem, energy, T0s, P0s, Y0s, t_ends
                ) -> np.ndarray:
        T0s = np.atleast_1d(np.asarray(T0s, np.float64))
        B = T0s.shape[0]
        P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
        Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                              (B, np.asarray(Y0s).shape[-1]))
        t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
        feats = sg_model.features(jnp.asarray(T0s), jnp.asarray(P0s),
                                  jnp.asarray(Y0s))
        log_tau = jnp.mean(sg_model.predict(model, feats)[..., 0],
                           axis=0)
        tau = np.asarray(10.0 ** log_tau, np.float64)
        return np.minimum(tau, t_ends)

    return predict
