"""Per-condition cost prediction: how the scheduler guesses which
elements are expensive BEFORE paying for a solve.

The default predictor is a mechanism-timescale estimate: a Gershgorin
row bound on the analytic RHS Jacobian at the initial state
(:func:`pychemkin_tpu.ops.jacobian.batch_rhs_jacobian` assembles it in
closed form — two skinny matmuls, one evaluation per condition, vs the
thousands a stiff solve performs). The bound caps the spectral radius
of J, i.e. the fastest chemical timescale 1/|lambda_max|; multiplied
by the integration horizon it is a dimensionless stiffness ratio — an
upper proxy for how many stiff steps the controller will take. The
ORDERING is what the scheduler consumes (cohorts form from ranks, not
absolute costs), so a monotone-correlated proxy is enough.

The served surrogate ensemble (PR 9) is an optional sharper predictor:
it prices ignition delay in ~0.07 ms, and a later-igniting condition
spends longer in the small-step induction window — pass the model to
:func:`surrogate_cost_predictor` and hand the result to the sweep's
``cost_fn``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import jacobian, reactors

#: jitted predictor programs keyed by (mech identity, problem, energy)
_COST_CACHE: Dict[Tuple, Any] = {}


def _cost_fn(mech, problem: str, energy: str):
    key = (id(mech), problem, energy)
    fn = _COST_CACHE.get(key)
    if fn is None:
        jac_fn = jacobian.batch_rhs_jacobian(problem, energy)

        def one(T0, P0, Y0, t_end):
            args, y0, _ = reactors.sweep_lane_args(mech, problem, T0,
                                                   P0, Y0)
            J = jac_fn(jnp.zeros((), dtype=y0.dtype), y0, args)
            # Gershgorin: max over rows of sum_j |J_ij| bounds the
            # spectral radius — the fastest timescale's rate
            rate = jnp.max(jnp.sum(jnp.abs(J), axis=1))
            return rate * t_end

        fn = _COST_CACHE[key] = jax.jit(jax.vmap(one))
    return fn


def stiffness_costs(mech, problem: str, energy: str, T0s, P0s, Y0s,
                    t_ends) -> np.ndarray:
    """Predicted relative cost [B] of each sweep condition: Gershgorin
    spectral-radius bound of the analytic Jacobian at t=0, times the
    integration horizon. All inputs broadcast along the batch axis
    exactly like :func:`~pychemkin_tpu.ops.reactors
    .ignition_delay_sweep`."""
    T0s = np.atleast_1d(np.asarray(T0s, np.float64))
    B = T0s.shape[0]
    P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
    Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                          (B, np.asarray(Y0s).shape[-1]))
    t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
    costs = _cost_fn(mech, problem, energy)(
        jnp.asarray(T0s), jnp.asarray(P0s), jnp.asarray(Y0s),
        jnp.asarray(t_ends))
    return np.asarray(costs, np.float64)


def surrogate_cost_predictor(model) -> Callable:
    """A sharper cost predictor from a trained ignition-delay
    surrogate (:mod:`pychemkin_tpu.surrogate`): predicted ignition
    delay, clamped to the horizon. A later-igniting condition holds
    the controller in its small-step induction window longer, so
    predicted delay orders integration cost. Returns a callable with
    the :func:`stiffness_costs` signature (mech/problem/energy are
    accepted and ignored — the model already encodes the chemistry).
    """
    from ..surrogate import model as sg_model

    def predict(mech, problem, energy, T0s, P0s, Y0s, t_ends
                ) -> np.ndarray:
        T0s = np.atleast_1d(np.asarray(T0s, np.float64))
        B = T0s.shape[0]
        P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
        Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                              (B, np.asarray(Y0s).shape[-1]))
        t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
        feats = sg_model.features(jnp.asarray(T0s), jnp.asarray(P0s),
                                  jnp.asarray(Y0s))
        log_tau = jnp.mean(sg_model.predict(model, feats)[..., 0],
                           axis=0)
        tau = np.asarray(10.0 ** log_tau, np.float64)
        return np.minimum(tau, t_ends)

    return predict
