"""Adaptive serving knobs: the batch window and effective batch cap
follow the live histograms instead of being a deployment-time guess.

The PR 8 tracing work showed the serve queue, not the solver, owning
latency under load (admission/batch-window spans dominating dispatch).
The two knobs that control that tradeoff — how long the first request
of a forming batch waits for company (``max_delay_ms``) and how large
a batch may grow before dispatching (``max_batch_size``) — have fixed
defaults. This controller retunes both from recent dispatches:

- **window**: half the recent p50 batch solve time, clamped to
  ``[window_min, window_max]`` — waiting much longer than half a
  solve adds latency without adding meaningful occupancy; waiting
  much less dispatches singletons under load.
- **batch cap**: the smallest warmed ladder rung covering the recent
  p95 occupancy, raised one rung when dispatches saturate the current
  cap. The cap NEVER exceeds the cap the server warmed with, and
  every value is a warmed rung — so adaptive mode provably triggers
  zero new XLA compiles (the ``serve.compiles`` invariant of PR 5).

The controller is pure bookkeeping (no jax, no threads): the server
calls :meth:`observe_batch` after each dispatch and applies the
returned knob dict when one is due. ``schedule.ladder_adjust`` counts
applied adjustments; a ``schedule.adjust`` event carries old/new.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry


class AdaptiveController:
    """Window/batch-cap controller over a warmed bucket ladder.

    ``ladder`` is the server's normalized bucket ladder;
    ``max_batch_size`` / ``max_delay_ms`` are the server's configured
    (and warmed) starting knobs — the cap ceiling and the window
    anchor. ``adjust_every`` dispatches between retunes bounds both
    the bookkeeping cost and the thrash rate."""

    def __init__(self, ladder: Sequence[int], *, max_batch_size: int,
                 max_delay_ms: float, adjust_every: int = 64,
                 history: int = 256, recorder=None,
                 window_bounds: Optional[tuple] = None):
        self.ladder = tuple(sorted({int(b) for b in ladder}))
        self.initial_cap = int(max_batch_size)
        self.initial_window_ms = float(max_delay_ms)
        self.cap = self.initial_cap
        self.window_ms = self.initial_window_ms
        self.adjust_every = max(1, int(adjust_every))
        if window_bounds is None:
            window_bounds = (min(0.25, self.initial_window_ms),
                             max(8.0 * self.initial_window_ms,
                                 self.initial_window_ms))
        self.window_bounds = (float(window_bounds[0]),
                              float(window_bounds[1]))
        self._occ = collections.deque(maxlen=int(history))
        self._solve_ms = collections.deque(maxlen=int(history))
        self._since_adjust = 0
        self.n_adjusts = 0
        self._rec = (recorder if recorder is not None
                     else telemetry.get_recorder())

    # -- observation -----------------------------------------------------
    def observe_batch(self, occupancy: int, solve_ms: float
                      ) -> Optional[Dict[str, float]]:
        """Record one dispatched batch; every ``adjust_every``
        dispatches, retune — returns ``{"max_delay_ms",
        "max_batch_size"}`` when the knobs moved, else None."""
        self._occ.append(int(occupancy))
        self._solve_ms.append(float(solve_ms))
        self._since_adjust += 1
        if self._since_adjust < self.adjust_every:
            return None
        self._since_adjust = 0
        return self._adjust()

    def _warmed_rungs(self) -> List[int]:
        return [b for b in self.ladder if b <= self.initial_cap]

    def _adjust(self) -> Optional[Dict[str, float]]:
        if not self._solve_ms:
            return None
        p50_solve = float(np.percentile(self._solve_ms, 50))
        p95_occ = float(np.percentile(self._occ, 95))
        new_window = float(np.clip(0.5 * p50_solve,
                                   self.window_bounds[0],
                                   self.window_bounds[1]))
        rungs = self._warmed_rungs()
        covering = [b for b in rungs if b >= p95_occ]
        new_cap = min(covering) if covering else self.initial_cap
        if new_cap < self.cap and p95_occ > 0.75 * new_cap:
            # shrink hysteresis: only step the cap down when p95
            # occupancy sits DECISIVELY inside the smaller rung —
            # otherwise shrink-then-saturate-then-reopen oscillates
            new_cap = self.cap
        if p95_occ >= self.cap and self.cap < self.initial_cap:
            # saturated at the current cap: open one warmed rung —
            # occupancy is censored at the cap, so covering-rung
            # selection alone can never climb back up. When no rung
            # sits strictly between cap and the configured ceiling
            # (initial_cap need not itself be a ladder rung), reopen
            # to the ceiling — the cap must never pin BELOW it
            above = [b for b in rungs if b > self.cap]
            new_cap = max(new_cap,
                          min(above) if above else self.initial_cap)
        window_moved = (abs(new_window - self.window_ms)
                        > 0.2 * max(self.window_ms, 1e-9))
        if not window_moved and new_cap == self.cap:
            return None
        old = (self.window_ms, self.cap)
        if window_moved:
            self.window_ms = new_window
        self.cap = new_cap
        self.n_adjusts += 1
        self._rec.inc("schedule.ladder_adjust")
        self._rec.event("schedule.adjust",
                        window_ms=round(self.window_ms, 3),
                        max_batch=self.cap,
                        prev_window_ms=round(old[0], 3),
                        prev_max_batch=old[1],
                        p50_solve_ms=round(p50_solve, 3),
                        p95_occupancy=round(p95_occ, 2))
        return {"max_delay_ms": self.window_ms,
                "max_batch_size": self.cap}

    # -- exposition ------------------------------------------------------
    def state(self) -> Dict:
        """JSON-ready controller state for metrics/chemtop."""
        occ = list(self._occ)
        return {
            "window_ms": round(self.window_ms, 3),
            "max_batch": self.cap,
            "initial_window_ms": round(self.initial_window_ms, 3),
            "initial_max_batch": self.initial_cap,
            "ladder": list(self.ladder),
            "adjusts": self.n_adjusts,
            "occupancy_p50": (float(np.percentile(occ, 50))
                              if occ else None),
        }
