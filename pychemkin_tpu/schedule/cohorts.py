"""Cohort planning: turn per-condition cost predictions into a batch
layout.

A chunked sweep solves contiguous index ranges; sorting the conditions
by predicted cost first means each chunk holds similar-cost elements —
the OpenFOAM load-balancing observation (arXiv:2112.05834) applied to
the vmapped-lockstep setting: a chunk's wall clock is its slowest
lane's step count, so mixing one stiff lane into a chunk of cheap ones
taxes the whole chunk. The plan is a pure permutation: the driver
solves (and checkpoints) in schedule order, and the inverse scatters
results back to caller order — values are untouched, so the scheduled
sweep stays bit-identical to the unsorted baseline per lane.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple, Optional

import numpy as np

from .. import telemetry


class CohortPlan(NamedTuple):
    """A scheduled batch layout.

    ``order[k]`` is the caller index solved at schedule position ``k``
    (ascending predicted cost; ties keep caller order — a stable sort,
    so equal-cost plans are deterministic). ``inverse`` scatters
    schedule-order arrays back: ``result[order] = scheduled`` i.e.
    ``result = scheduled[inverse]``."""
    order: np.ndarray
    inverse: np.ndarray
    n_cohorts: int
    chunk: int

    @property
    def is_identity(self) -> bool:
        return bool(np.array_equal(self.order,
                                   np.arange(self.order.size)))


def plan_cohorts(costs, chunk: int, *, recorder=None,
                 label: str = "") -> CohortPlan:
    """Sort ``costs`` [B] into ascending-cost cohorts of ``chunk``
    elements. Emits the ``schedule.cohorts`` counter (one per cohort
    chunk) and a ``schedule.plan`` event carrying the cost spread —
    the evidence of how mixed the batch actually was."""
    costs = np.asarray(costs, np.float64)
    if costs.ndim != 1 or costs.size == 0:
        raise ValueError(f"costs must be a non-empty 1-D array, got "
                         f"shape {costs.shape}")
    B = costs.size
    chunk = max(1, min(int(chunk), B))
    # non-finite predictions sort LAST (treated as most expensive):
    # a predictor overflow must not scramble the finite ordering
    keys = np.where(np.isfinite(costs), costs, np.inf)
    order = np.argsort(keys, kind="stable")
    inverse = np.empty(B, dtype=np.int64)
    inverse[order] = np.arange(B)
    n_cohorts = -(-B // chunk)
    rec = recorder if recorder is not None else telemetry.get_recorder()
    rec.inc("schedule.cohorts", n_cohorts)
    finite = costs[np.isfinite(costs)]
    rec.event("schedule.plan", label=label, B=B, chunk=chunk,
              n_cohorts=n_cohorts,
              cost_min=float(finite.min()) if finite.size else None,
              cost_max=float(finite.max()) if finite.size else None,
              cost_spread=(float(finite.max() / max(finite.min(),
                                                    1e-300))
                           if finite.size else None))
    return CohortPlan(order=order, inverse=inverse,
                      n_cohorts=n_cohorts, chunk=chunk)


def order_signature(order: Optional[np.ndarray]) -> str:
    """Checkpoint-salt for a schedule order: a banked manifest stores
    results in SCHEDULE order, so a resume under a different (or no)
    order must not adopt it — salting the problem signature makes the
    mismatch a clean nothing-banked miss instead of scrambled lanes."""
    if order is None:
        return "static"
    h = hashlib.sha256(np.ascontiguousarray(
        np.asarray(order, np.int64)).tobytes())
    return h.hexdigest()[:16]
