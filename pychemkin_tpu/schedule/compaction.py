"""Mid-sweep compaction: stop paying batch slots for finished lanes.

A vmapped stiff integration runs its ``while_loop`` until the LAST
lane reaches the horizon; every iteration costs the full batch width.
This driver instead advances the batch in bounded step-rounds
(:func:`pychemkin_tpu.ops.reactors.ignition_sweep_kernel`), harvests
finished lanes on the host between rounds, and gathers the still-
active lanes into the smallest fitting bucket of a FIXED shape ladder
— so a batch that starts 256 wide finishes its stragglers 32 wide,
and the per-iteration cost tracks the live population instead of the
initial one.

Compiled-shape discipline: every shape the driver ever dispatches is a
ladder rung (descending powers of two from the starting width), and
the kernel's jitted entry points are shape-keyed — after each rung's
first run (or a warmed persistent-XLA-cache hit) the sweep triggers
zero new compiles. Padding lanes are edge duplicates of a live lane;
their results are discarded by global-index bookkeeping.

Bit-match: rounds share the one-shot integrator's step body
(``odeint._segment_fns``) and lane values are independent of batch
companions, so harvested results match the compiled unsorted vmapped
sweep up to XLA:CPU's per-program-width fusion rounding — bitwise
where the rung widths lower identically (property-tested on both
embedded mechanisms in tests/test_schedule.py), at worst ~1e-13
relative on GRI-scale mechanisms across widely differing widths
(see the MIN_BUCKET note).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs, telemetry
from ..mechanism import costmodel
from ..obs import programs as obs_programs
from ..ops import kinetics, reactors
from ..ops.odeint import solve_profile_enabled
from ..resilience import faultinject
from ..resilience.driver import edge_pad_indices

#: step attempts per round between host harvests; the knob trades host
#: round-trip overhead (one gather + mask read per round) against
#: compaction granularity
ROUND_ENV = "PYCHEMKIN_COMPACT_ROUND"
DEFAULT_ROUND_LEN = 512

#: smallest compaction bucket — a HARD floor, not a tuning default:
#: below ~8 lanes XLA:CPU lowers the batched step math differently
#: (vectorization threshold), far outside the rounding band the
#: compaction contract allows. Above the floor, per-lane width-
#: invariance is mechanism-dependent: h2o2 (11 states) is bitwise
#: across all widths >= 8, while grisyn (54 states) can pick up
#: ~1e-13-relative fusion-rounding differences between widely
#: differing program widths (measured 8 vs 64 — the band the
#: batch-efficiency rung already documents; adjacent rungs like
#: 16 vs 8 bit-match, see tests/test_schedule.py). The floor also
#: marks where per-iteration fixed cost dominates.
MIN_BUCKET = 8

#: resumable-sweep kernels keyed by full solver configuration (incl.
#: the active fault specs — injection is a trace-time decision, so a
#: kernel traced clean must not serve an injected sweep)
_KERNEL_CACHE: Dict[Tuple, Any] = {}


def _align(b: int, unit: int = MIN_BUCKET) -> int:
    """Round a width up to the ``unit`` lane multiple (``unit`` itself
    always a MIN_BUCKET multiple) — keeps every rung on the vectorized
    lowering path (XLA:CPU peels non-multiple tails onto a
    differently-rounding scalar path)."""
    return -(-int(b) // unit) * unit


def compaction_ladder(top: int, min_bucket: int = MIN_BUCKET,
                      lane_multiple: int = MIN_BUCKET
                      ) -> Tuple[int, ...]:
    """Descending shape ladder from ``top``: halving rungs, every rung
    aligned to the ``lane_multiple`` (itself rounded up to a MIN_BUCKET
    multiple) and floored at ``max(min_bucket, lane_multiple)``
    (raising ``min_bucket`` is a perf knob; lowering it below the
    invariance floor is not possible). A multi-device sweep passes
    ``lane_multiple = MIN_BUCKET * n_devices`` so every rung divides
    evenly into identically-shaped, 8-aligned per-shard blocks — the
    ladder is then the SAME on every device and zero new programs
    compile after each rung's first run."""
    top = int(top)
    if top < 1:
        raise ValueError(f"ladder top must be positive, got {top}")
    unit = _align(max(int(lane_multiple), MIN_BUCKET))
    floor = _align(max(int(min_bucket), unit), unit)
    rungs = [_align(top, unit)]
    b = rungs[0] // 2
    while _align(b, unit) >= floor and len(rungs) < 6:
        if _align(b, unit) != rungs[-1]:
            rungs.append(_align(b, unit))
        b //= 2
    return tuple(rungs)


def _round_len() -> int:
    return int(knobs.value(ROUND_ENV))


def _kernel(mech, problem, energy, cfg: Tuple, kwargs: Dict):
    key = (id(mech), problem, energy, cfg, faultinject.specs())
    k = _KERNEL_CACHE.get(key)
    if k is None:
        k = _KERNEL_CACHE[key] = reactors.ignition_sweep_kernel(
            mech, problem, energy, **kwargs)
    return k


#: shard_map-wrapped kernel entry points, one triple per
#: (kernel, mesh-devices): the jit objects must be LONG-LIVED so the
#: per-rung shape cache survives across sweeps (zero new compiles
#: after warmup is part of the multi-device contract)
_MESH_PROGRAM_CACHE: Dict[Tuple, Any] = {}


def _mesh_programs(kernel, mesh):
    """The kernel's ``(init, advance, harvest)`` wrapped in one
    ``shard_map`` over the mesh batch axis: each device runs the plain
    lane programs on its ``width // n_devices`` block — lane values
    never depend on batch companions or shard placement, so harvested
    results agree with the single-device sweep up to XLA:CPU's
    per-program-width fusion rounding (bitwise on h2o2, ~1e-13
    relative on grisyn; see the MIN_BUCKET note)."""
    # lazy: parallel.sharding routes INTO this module (compact path),
    # so a top-level import here would be a genuine cycle
    from ..parallel.sharding import BATCH_AXIS, shard_map
    key = (id(kernel), tuple(d.id for d in mesh.devices.flat))
    progs = _MESH_PROGRAM_CACHE.get(key)
    if progs is None:
        spec = jax.sharding.PartitionSpec(BATCH_AXIS)

        def _wrap(fn, n_args):
            return jax.jit(shard_map(
                fn, mesh=mesh, in_specs=(spec,) * n_args,
                out_specs=spec, check_vma=False))

        progs = (_wrap(kernel.init, 5), _wrap(kernel.advance, 6),
                 _wrap(kernel.harvest, 6))
        _MESH_PROGRAM_CACHE[key] = progs
    return progs


def compacted_ignition_sweep(mech, problem, energy, T0s, P0s, Y0s,
                             t_ends, *, rtol=1e-6, atol=1e-12,
                             ignition_mode=None, ignition_kwargs=None,
                             max_steps_per_segment=20_000, h0=0.0,
                             jac_mode="analytic",
                             elem_ids: Optional[Sequence[int]] = None,
                             fault_level: int = 0,
                             ladder: Optional[Sequence[int]] = None,
                             round_len: Optional[int] = None,
                             mesh=None,
                             recorder=None, label: str = ""
                             ) -> Dict[str, np.ndarray]:
    """Batched ignition-delay sweep with mid-sweep compaction.

    Same contract as
    :func:`~pychemkin_tpu.ops.reactors.ignition_delay_sweep` (results
    match it at the compiled-baseline level, up to the
    per-program-width rounding band in the module docstring), returned
    as a dict
    of [B] arrays ``times``/``ok``/``status`` plus the per-element
    solver counters ``n_steps``/``n_rejected``/``n_newton`` the bench
    FLOP model sums (and, when ``PYCHEMKIN_SOLVE_PROFILE`` is on,
    the physics extras ``dt_min``/``dt_final``/``stiffness``).
    ``elem_ids`` carries ORIGINAL batch indices for
    fault injection — a cohort-permuted scheduled sweep passes the
    caller ids so the same elements stay poisoned.

    ``mesh`` (a ``jax.sharding.Mesh`` over the batch axis) runs every
    round shard_mapped across its devices and re-bins survivors
    GLOBALLY between rounds: finished lanes anywhere on the mesh free
    batch slots everywhere, instead of stranding per-shard stragglers.
    Ladder rungs are aligned to ``MIN_BUCKET * n_devices`` so each
    shard's block is 8-aligned and identically shaped on every device;
    re-binning is a host gather + re-scatter of the carried state
    (O(width) state bytes per compaction, same bookkeeping as the
    single-device path). Per-lane math never depends on batch
    companions or shard placement, so caller-order results match the
    single-device sweep through the same kernel up to per-program-width
    fusion rounding: bitwise on h2o2 (property-tested), ~1e-13
    relative on GRI-scale mechanisms — the same band the
    batch-efficiency rung documents. Statuses agree except for lanes
    sitting exactly on the step-budget boundary, where a last-bit
    difference can flip ``BUDGET_EXHAUSTED`` <-> ``OK``.
    """
    if ignition_mode is None:
        ignition_mode = reactors.IGN_T_INFLECTION
    T0s = np.atleast_1d(np.asarray(T0s, np.float64))
    B = T0s.shape[0]
    P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
    Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                          (B, np.asarray(Y0s).shape[-1]))
    t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
    if elem_ids is None:
        elem_ids = np.arange(B)
    elem_ids = np.asarray(elem_ids, np.int64)
    if elem_ids.shape != (B,):
        raise ValueError(f"elem_ids must have shape ({B},), got "
                         f"{elem_ids.shape}")
    rl = int(round_len) if round_len is not None else _round_len()
    # the in-kernel physics profile (PYCHEMKIN_SOLVE_PROFILE) is a
    # trace-time decision, so it keys the kernel cache exactly like
    # the fault specs: a kernel traced profile-off must not serve a
    # profiled sweep (and vice versa)
    prof = solve_profile_enabled()
    kwargs = dict(rtol=rtol, atol=atol, ignition_mode=ignition_mode,
                  ignition_kwargs=ignition_kwargs,
                  max_steps_per_segment=max_steps_per_segment, h0=h0,
                  jac_mode=jac_mode, fault_level=fault_level,
                  round_len=rl, profile=prof)
    cfg = (rtol, atol, str(ignition_mode),
           tuple(sorted((ignition_kwargs or {}).items())),
           max_steps_per_segment, h0, jac_mode, fault_level, rl, prof)
    kernel = _kernel(mech, problem, energy, cfg, kwargs)
    n_dev = int(mesh.size) if mesh is not None else 1
    if n_dev <= 1:
        mesh = None                 # 1-device mesh == plain path
        n_dev = 1
    unit = _align(MIN_BUCKET * n_dev)
    if ladder is None:
        ladder = compaction_ladder(B, lane_multiple=unit)
    # the MIN_BUCKET floor/alignment is part of the bit-match
    # contract (see above): an explicit ladder cannot opt into sub-8
    # or non-8-multiple shapes — every rung is aligned up, deduped
    # (on a mesh, up to the per-shard-identical 8*n_dev multiple)
    rungs = tuple(sorted({_align(b, unit) for b in ladder
                          if int(b) >= 1}, reverse=True))
    if not rungs or rungs[0] < B:
        rungs = (_align(max(B, unit), unit),) + rungs
    rec = recorder if recorder is not None else telemetry.get_recorder()

    if mesh is None:
        init_p, advance_p, harvest_p = (kernel.init, kernel.advance,
                                        kernel.harvest)
        place = None
    else:
        init_p, advance_p, harvest_p = _mesh_programs(kernel, mesh)
        from ..parallel.sharding import BATCH_AXIS
        named = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(BATCH_AXIS))

        def place(tree):
            # commit every (re-binned) carry to the mesh sharding so
            # each rung's program compiles exactly once — an eagerly
            # gathered, uncommitted carry would key a second cache
            # entry for the same shape
            return jax.device_put(tree, named)

    out = {
        "times": np.full(B, np.nan),
        "ok": np.zeros(B, bool),
        "status": np.zeros(B, np.int32),
        "n_steps": np.zeros(B, np.int64),
        "n_rejected": np.zeros(B, np.int64),
        "n_newton": np.zeros(B, np.int64),
    }
    if prof:
        out["dt_min"] = np.full(B, np.nan)
        out["dt_final"] = np.full(B, np.nan)
        out["stiffness"] = np.full(B, np.nan)

    # -- program observatory: one registered program per ladder rung.
    # The rung's resolved config binds the trace-time knobs its jit
    # programs resolve; its id is stable across sweeps/respawns, so
    # rung wall and model FLOPs aggregate per compiled shape.
    registry = obs_programs.get_registry()
    mech_sig = obs_programs.mech_signature(mech)
    staged = getattr(mech, "rop_stage", None) is not None
    rop_mode = ("sparse" if (staged
                             and kinetics.resolve_rop_mode() == "sparse")
                else "dense")
    fused = jac_mode == "analytic" and kinetics.fused_enabled(mech)
    sweep_cfg = {
        "rop_mode": rop_mode,
        "fuse_mode": "fused" if fused else "split",
        "jac_mode": jac_mode, "profile": prof,
        "rtol": rtol, "atol": atol,
        "max_steps": int(max_steps_per_segment),
        "round_len": rl, "fault_level": int(fault_level),
        "n_devices": n_dev,
        "schedule": knobs.value("PYCHEMKIN_SCHEDULE"),
    }
    _rung_pids: Dict[int, str] = {}

    def _rung_pid(w: int) -> str:
        pid = _rung_pids.get(w)
        if pid is None:
            pid = obs_programs.program_id(mech_sig, "sweep.ignition",
                                          (w,), sweep_cfg)
            registry.register(pid, kind="sweep.ignition",
                              mech_sig=mech_sig, shape=(w,),
                              config=sweep_cfg)
            _rung_pids[w] = pid
        return pid

    def _bank_round(w: int, wall_ms: float, d_attempts: float,
                    d_newtons: float, hits_before: int,
                    compiled: bool) -> None:
        # model FLOPs of this round's REAL work: the cumulative-counter
        # deltas over the current batch (padding lanes included — edge
        # duplicates burn real hardware FLOPs)
        gflop = costmodel.integration_flops(
            mech, d_attempts, d_newtons, rop_mode=rop_mode,
            jac_mode=jac_mode if jac_mode in ("analytic", "ad")
            else "analytic", fused=fused) / 1e9
        hits_delta = (obs_programs.cache_hits() - hits_before
                      if compiled and hits_before >= 0 else None)
        registry.record_dispatch(
            _rung_pid(w), wall_ms, model_gflop=gflop,
            compiled=compiled, cache_hits_delta=hits_delta,
            recorder=rec)
        rec.observe("sweep.solve_ms", wall_ms)

    def _gather(arrs, idx):
        return [jax.tree_util.tree_map(lambda a: a[idx], c)
                for c in arrs]

    # start at the smallest rung holding the whole batch, edge-padded
    width = min(b for b in rungs if b >= B)
    pad = edge_pad_indices(0, B, width)
    gidx = pad.copy()            # caller index carried by each lane
    inputs = [jnp.asarray(a) for a in
              _gather([T0s, P0s, Y0s, t_ends, elem_ids], pad)]
    if place is not None:
        inputs = [place(a) for a in inputs]
    # the first round's wall includes init (its compile is part of the
    # top rung's first-dispatch cost); cumulative-counter baselines
    # start at zero for the freshly padded batch
    prev = {k: np.zeros(width, np.int64)
            for k in ("n_steps", "n_rejected", "n_newton")}
    round_t0 = time.perf_counter()
    state = init_p(*inputs)

    n_compactions = 0
    rounds = 0
    # each round advances every active lane by >=1 attempt (or it is
    # done), so attempts bound the round count; the +8 covers the
    # all-lanes-finish-early exits
    max_rounds = -(-int(max_steps_per_segment) * 2 // max(rl, 1)) + 8
    harvested = np.zeros(B, bool)
    while True:
        compiled = registry.dispatches(_rung_pid(width)) == 0
        hits_before = obs_programs.cache_hits() if compiled else -1
        state = advance_p(state, *inputs)
        h = {k: np.asarray(v) for k, v in
             harvest_p(state, *inputs).items()}
        # np.asarray above forces the host transfer, so this wall is
        # device-fenced — one round = one dispatch of the rung program
        wall_ms = (time.perf_counter() - round_t0) * 1e3
        rounds += 1
        d_attempts = float((h["n_steps"] - prev["n_steps"]).sum()
                           + (h["n_rejected"]
                              - prev["n_rejected"]).sum())
        d_newtons = float((h["n_newton"] - prev["n_newton"]).sum())
        prev = {k: h[k] for k in prev}
        _bank_round(width, wall_ms, d_attempts, d_newtons,
                    hits_before, compiled)
        done = h["done"]
        new = done & ~harvested[gidx]
        if new.any():
            # first write wins per caller index (pad duplicates carry
            # identical trajectories, so any-write is equivalent; the
            # mask keeps the bookkeeping single-touch)
            sel = np.nonzero(new)[0]
            _, first = np.unique(gidx[sel], return_index=True)
            sel = sel[first]
            tgt = gidx[sel]
            for key in out:
                out[key][tgt] = h[key][sel]
            harvested[tgt] = True
        active = ~done
        n_active = len(set(gidx[active]))
        if n_active == 0:
            break
        if rounds >= max_rounds:   # pragma: no cover — defensive
            raise RuntimeError(
                f"compacted sweep did not converge in {rounds} rounds "
                f"({n_active} lanes still active)")
        fitting = [b for b in rungs if b >= n_active]
        bucket = min(fitting) if fitting else rungs[0]
        if bucket < width:
            sel = np.nonzero(active)[0]
            # keep one lane per distinct caller index, drop stale pads
            _, first = np.unique(gidx[sel], return_index=True)
            sel = sel[np.sort(first)]
            pad = np.concatenate(
                [sel, np.repeat(sel[-1], bucket - sel.size)])
            # the gather is GLOBAL on a mesh: survivor lanes from any
            # shard re-bin into any slot of the next (smaller) rung
            state = jax.tree_util.tree_map(lambda a: a[pad], state)
            inputs = [jax.tree_util.tree_map(lambda a: a[pad], c)
                      for c in inputs]
            if place is not None:
                state = place(state)
                inputs = [place(a) for a in inputs]
                rec.inc("schedule.mesh_rebins")
            gidx = gidx[pad]
            prev = {k: prev[k][pad] for k in prev}
            width = bucket
            n_compactions += 1
            rec.inc("schedule.compactions")
        round_t0 = time.perf_counter()
    rec.event("schedule.compaction", label=label, B=B,
              rounds=rounds, n_compactions=n_compactions,
              ladder=list(rungs), round_len=rl, n_devices=n_dev)
    return out
