"""Mid-sweep compaction: stop paying batch slots for finished lanes.

A vmapped stiff integration runs its ``while_loop`` until the LAST
lane reaches the horizon; every iteration costs the full batch width.
This driver instead advances the batch in bounded step-rounds
(:func:`pychemkin_tpu.ops.reactors.ignition_sweep_kernel`), harvests
finished lanes on the host between rounds, and gathers the still-
active lanes into the smallest fitting bucket of a FIXED shape ladder
— so a batch that starts 256 wide finishes its stragglers 32 wide,
and the per-iteration cost tracks the live population instead of the
initial one.

Compiled-shape discipline: every shape the driver ever dispatches is a
ladder rung (descending powers of two from the starting width), and
the kernel's jitted entry points are shape-keyed — after each rung's
first run (or a warmed persistent-XLA-cache hit) the sweep triggers
zero new compiles. Padding lanes are edge duplicates of a live lane;
their results are discarded by global-index bookkeeping.

Bit-match: rounds share the one-shot integrator's step body
(``odeint._segment_fns``) and lane values are independent of batch
companions, so harvested results are bit-identical to the compiled
unsorted vmapped sweep — property-tested in tests/test_schedule.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs, telemetry
from ..ops import reactors
from ..ops.odeint import solve_profile_enabled
from ..resilience import faultinject
from ..resilience.driver import edge_pad_indices

#: step attempts per round between host harvests; the knob trades host
#: round-trip overhead (one gather + mask read per round) against
#: compaction granularity
ROUND_ENV = "PYCHEMKIN_COMPACT_ROUND"
DEFAULT_ROUND_LEN = 512

#: smallest compaction bucket — a HARD floor, not a tuning default:
#: below ~8 lanes XLA:CPU lowers the batched step math differently
#: (vectorization threshold), breaking the per-lane bitwise width-
#: invariance the compaction contract rests on (measured: widths
#: >= 8 are bit-invariant on both embedded mechanisms, widths 1-4
#: are not). It also marks where per-iteration fixed cost dominates.
MIN_BUCKET = 8

#: resumable-sweep kernels keyed by full solver configuration (incl.
#: the active fault specs — injection is a trace-time decision, so a
#: kernel traced clean must not serve an injected sweep)
_KERNEL_CACHE: Dict[Tuple, Any] = {}


def _align(b: int) -> int:
    """Round a width up to the MIN_BUCKET lane multiple — the bitwise
    width-invariance domain (XLA:CPU peels non-multiple tails onto a
    differently-rounding scalar path)."""
    return -(-int(b) // MIN_BUCKET) * MIN_BUCKET


def compaction_ladder(top: int, min_bucket: int = MIN_BUCKET
                      ) -> Tuple[int, ...]:
    """Descending shape ladder from ``top``: halving rungs, every rung
    aligned to the MIN_BUCKET lane multiple and floored at
    ``max(min_bucket, MIN_BUCKET)`` (raising ``min_bucket`` is a perf
    knob; lowering it below the invariance floor is not possible)."""
    top = int(top)
    if top < 1:
        raise ValueError(f"ladder top must be positive, got {top}")
    floor = _align(max(int(min_bucket), MIN_BUCKET))
    rungs = [_align(top)]
    b = rungs[0] // 2
    while _align(b) >= floor and len(rungs) < 6:
        if _align(b) != rungs[-1]:
            rungs.append(_align(b))
        b //= 2
    return tuple(rungs)


def _round_len() -> int:
    return int(knobs.value(ROUND_ENV))


def _kernel(mech, problem, energy, cfg: Tuple, kwargs: Dict):
    key = (id(mech), problem, energy, cfg, faultinject.specs())
    k = _KERNEL_CACHE.get(key)
    if k is None:
        k = _KERNEL_CACHE[key] = reactors.ignition_sweep_kernel(
            mech, problem, energy, **kwargs)
    return k


def compacted_ignition_sweep(mech, problem, energy, T0s, P0s, Y0s,
                             t_ends, *, rtol=1e-6, atol=1e-12,
                             ignition_mode=None, ignition_kwargs=None,
                             max_steps_per_segment=20_000, h0=0.0,
                             jac_mode="analytic",
                             elem_ids: Optional[Sequence[int]] = None,
                             fault_level: int = 0,
                             ladder: Optional[Sequence[int]] = None,
                             round_len: Optional[int] = None,
                             recorder=None, label: str = ""
                             ) -> Dict[str, np.ndarray]:
    """Batched ignition-delay sweep with mid-sweep compaction.

    Same contract as
    :func:`~pychemkin_tpu.ops.reactors.ignition_delay_sweep` (results
    bit-match it at the compiled-baseline level), returned as a dict
    of [B] arrays ``times``/``ok``/``status`` plus the per-element
    solver counters ``n_steps``/``n_rejected``/``n_newton`` the bench
    FLOP model sums (and, when ``PYCHEMKIN_SOLVE_PROFILE`` is on,
    the physics extras ``dt_min``/``dt_final``/``stiffness``).
    ``elem_ids`` carries ORIGINAL batch indices for
    fault injection — a cohort-permuted scheduled sweep passes the
    caller ids so the same elements stay poisoned.
    """
    if ignition_mode is None:
        ignition_mode = reactors.IGN_T_INFLECTION
    T0s = np.atleast_1d(np.asarray(T0s, np.float64))
    B = T0s.shape[0]
    P0s = np.broadcast_to(np.asarray(P0s, np.float64), (B,))
    Y0s = np.broadcast_to(np.asarray(Y0s, np.float64),
                          (B, np.asarray(Y0s).shape[-1]))
    t_ends = np.broadcast_to(np.asarray(t_ends, np.float64), (B,))
    if elem_ids is None:
        elem_ids = np.arange(B)
    elem_ids = np.asarray(elem_ids, np.int64)
    if elem_ids.shape != (B,):
        raise ValueError(f"elem_ids must have shape ({B},), got "
                         f"{elem_ids.shape}")
    rl = int(round_len) if round_len is not None else _round_len()
    # the in-kernel physics profile (PYCHEMKIN_SOLVE_PROFILE) is a
    # trace-time decision, so it keys the kernel cache exactly like
    # the fault specs: a kernel traced profile-off must not serve a
    # profiled sweep (and vice versa)
    prof = solve_profile_enabled()
    kwargs = dict(rtol=rtol, atol=atol, ignition_mode=ignition_mode,
                  ignition_kwargs=ignition_kwargs,
                  max_steps_per_segment=max_steps_per_segment, h0=h0,
                  jac_mode=jac_mode, fault_level=fault_level,
                  round_len=rl, profile=prof)
    cfg = (rtol, atol, str(ignition_mode),
           tuple(sorted((ignition_kwargs or {}).items())),
           max_steps_per_segment, h0, jac_mode, fault_level, rl, prof)
    kernel = _kernel(mech, problem, energy, cfg, kwargs)
    if ladder is None:
        ladder = compaction_ladder(B)
    # the MIN_BUCKET floor/alignment is part of the bit-match
    # contract (see above): an explicit ladder cannot opt into sub-8
    # or non-8-multiple shapes — every rung is aligned up, deduped
    rungs = tuple(sorted({_align(b) for b in ladder if int(b) >= 1},
                         reverse=True))
    if not rungs or rungs[0] < B:
        rungs = (_align(max(B, MIN_BUCKET)),) + rungs
    rec = recorder if recorder is not None else telemetry.get_recorder()

    out = {
        "times": np.full(B, np.nan),
        "ok": np.zeros(B, bool),
        "status": np.zeros(B, np.int32),
        "n_steps": np.zeros(B, np.int64),
        "n_rejected": np.zeros(B, np.int64),
        "n_newton": np.zeros(B, np.int64),
    }
    if prof:
        out["dt_min"] = np.full(B, np.nan)
        out["dt_final"] = np.full(B, np.nan)
        out["stiffness"] = np.full(B, np.nan)

    def _gather(arrs, idx):
        return [jax.tree_util.tree_map(lambda a: a[idx], c)
                for c in arrs]

    # start at the smallest rung holding the whole batch, edge-padded
    width = min(b for b in rungs if b >= B)
    pad = edge_pad_indices(0, B, width)
    gidx = pad.copy()            # caller index carried by each lane
    inputs = [jnp.asarray(a) for a in
              _gather([T0s, P0s, Y0s, t_ends, elem_ids], pad)]
    state = kernel.init(*inputs)

    n_compactions = 0
    rounds = 0
    # each round advances every active lane by >=1 attempt (or it is
    # done), so attempts bound the round count; the +8 covers the
    # all-lanes-finish-early exits
    max_rounds = -(-int(max_steps_per_segment) * 2 // max(rl, 1)) + 8
    harvested = np.zeros(B, bool)
    while True:
        state = kernel.advance(state, *inputs)
        h = {k: np.asarray(v) for k, v in
             kernel.harvest(state, *inputs).items()}
        rounds += 1
        done = h["done"]
        new = done & ~harvested[gidx]
        if new.any():
            # first write wins per caller index (pad duplicates carry
            # identical trajectories, so any-write is equivalent; the
            # mask keeps the bookkeeping single-touch)
            sel = np.nonzero(new)[0]
            _, first = np.unique(gidx[sel], return_index=True)
            sel = sel[first]
            tgt = gidx[sel]
            for key in out:
                out[key][tgt] = h[key][sel]
            harvested[tgt] = True
        active = ~done
        n_active = len(set(gidx[active]))
        if n_active == 0:
            break
        if rounds >= max_rounds:   # pragma: no cover — defensive
            raise RuntimeError(
                f"compacted sweep did not converge in {rounds} rounds "
                f"({n_active} lanes still active)")
        fitting = [b for b in rungs if b >= n_active]
        bucket = min(fitting) if fitting else rungs[0]
        if bucket < width:
            sel = np.nonzero(active)[0]
            # keep one lane per distinct caller index, drop stale pads
            _, first = np.unique(gidx[sel], return_index=True)
            sel = sel[np.sort(first)]
            pad = np.concatenate(
                [sel, np.repeat(sel[-1], bucket - sel.size)])
            state = jax.tree_util.tree_map(lambda a: a[pad], state)
            inputs = [jax.tree_util.tree_map(lambda a: a[pad], c)
                      for c in inputs]
            gidx = gidx[pad]
            width = bucket
            n_compactions += 1
            rec.inc("schedule.compactions")
    rec.event("schedule.compaction", label=label, B=B,
              rounds=rounds, n_compactions=n_compactions,
              ladder=list(rungs), round_len=rl)
    return out
