"""Stiffness-aware scheduling: cohort binning, mid-sweep compaction,
and adaptive serving knobs.

A vmapped batch integrates at the pace of its stiffest element: the
``while_loop`` masks finished lanes into no-ops but keeps paying their
per-iteration wall clock (the BENCH_r05 inversion — grisyn B=256 was
*slower per element* than B=64). This package turns the fixed batch
layout into a scheduled one, in three layers:

- **Predict** (:mod:`.predictor`): a cheap per-condition cost estimate
  — a Gershgorin bound on the analytic Jacobian at t=0 times the
  integration horizon (one Jacobian evaluation per condition, vs the
  thousands a solve performs), with the served surrogate ensemble as
  an optional sharper predictor.
- **Sort & compact** (:mod:`.cohorts`, :mod:`.compaction`): sweep
  conditions are sorted into stiffness cohorts before chunking, so
  each compiled chunk holds similar-cost elements, and long
  integrations run as bounded step-rounds with finished lanes
  compacted out of the batch between rounds (shapes stay on a fixed
  bucket ladder — zero new compiles after each shape's first run).
  A permutation layer scatters results back to caller order; the
  per-lane step math is shared with the one-shot integrator
  (``odeint._segment_fns``), so scheduled results BIT-MATCH the
  unsorted compiled vmapped baseline.
- **Adapt** (:mod:`.adaptive`): the serve layer's batch-window and
  effective batch-size knobs are driven by the live occupancy /
  solve-time histograms instead of being a fixed guess; every choice
  stays on the warmed bucket ladder so steady traffic never compiles.

Mode knob ``PYCHEMKIN_SCHEDULE`` (explicit call arguments win):

- ``static``    (default) — the pre-scheduling behavior everywhere.
- ``sorted``    — sweeps sort into cohorts and compact mid-sweep.
- ``adaptive``  — ``sorted`` plus the serve layer's adaptive
  window/batch-cap controller.

Telemetry: ``schedule.cohorts`` (cohort chunks planned),
``schedule.compactions`` (mid-sweep gathers), and
``schedule.ladder_adjust`` (serve knob adjustments) counters, plus a
``schedule`` field on every ``serve.dispatch`` trace span. Every
scheduled sweep additionally banks its predicted-vs-measured cost
rank correlation (``schedule.predictor_corr`` gauge +
``schedule.calibration`` event, mirrored into ``job_report``) — the
live calibration signal that tells an operator when the Gershgorin
predictor has gone stale and ``cost_fn`` should switch to the
surrogate (:func:`bank_predictor_calibration`).
"""

from __future__ import annotations

from .. import knobs
from .adaptive import AdaptiveController
from .cohorts import CohortPlan, order_signature, plan_cohorts
from .compaction import compacted_ignition_sweep, compaction_ladder
from .predictor import (bank_predictor_calibration, spearman,
                        stiffness_costs, surrogate_cost_predictor)

#: valid PYCHEMKIN_SCHEDULE values
MODES = ("static", "sorted", "adaptive")

#: the scheduling mode knob (read at call time, so live processes
#: re-resolve per sweep/server build)
MODE_ENV = "PYCHEMKIN_SCHEDULE"

#: the counters this package emits — schema-asserted in test_telemetry
SCHEDULE_COUNTERS = ("schedule.cohorts", "schedule.compactions",
                     "schedule.ladder_adjust")

#: the trace-span field carrying the mode on serve dispatch spans
SCHEDULE_SPAN_FIELD = "schedule"

__all__ = [
    "AdaptiveController", "CohortPlan", "MODES", "MODE_ENV",
    "SCHEDULE_COUNTERS", "SCHEDULE_SPAN_FIELD",
    "bank_predictor_calibration", "compacted_ignition_sweep",
    "compaction_ladder", "order_signature", "plan_cohorts",
    "resolve_mode", "spearman", "stiffness_costs",
    "surrogate_cost_predictor",
]


def resolve_mode(mode: str | None = None) -> str:
    """The active scheduling mode: the explicit argument when given,
    else ``PYCHEMKIN_SCHEDULE``, else ``static``. An unknown value is
    rejected loudly — a typo'd knob silently running static would fake
    a scheduling A/B."""
    if mode is None:
        # registry-validated: an unknown env value raises naming the
        # knob and the valid choices
        return knobs.value(MODE_ENV)
    if mode not in MODES:
        raise ValueError(
            f"unknown schedule mode {mode!r} (explicit); "
            f"expected one of {MODES}")
    return mode
