"""Singleton logger with the reference's surface
(reference: src/ansys/chemkin/logger.py:32-127).

Default level is ERROR; ``enable_output`` attaches a stream handler,
``add_file_handler`` writes to ``./.log/chemkin_service.log``.
"""

from __future__ import annotations

import logging
import os


class SingletonType(type):
    """Metaclass making every instantiation return the same object
    (reference: logger.py:32-42)."""

    _instances: dict = {}

    def __call__(cls, *args, **kwargs):
        if cls not in cls._instances:
            cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]


class ChemkinLogger(metaclass=SingletonType):
    """Thin wrapper over :mod:`logging` (reference: logger.py:44-127)."""

    def __init__(self) -> None:
        self._logger = logging.getLogger("pychemkin_tpu")
        self._logger.setLevel(logging.ERROR)
        self._stream_handler: logging.Handler | None = None
        self._file_handler: logging.Handler | None = None

    # -- level control -------------------------------------------------------
    def set_level(self, level) -> None:
        if isinstance(level, str):
            level = getattr(logging, level.upper())
        self._logger.setLevel(level)

    def get_level(self) -> int:
        return self._logger.level

    # -- handlers ------------------------------------------------------------
    def enable_output(self, stream=None) -> None:
        if self._stream_handler is None:
            handler = logging.StreamHandler(stream)
            handler.setFormatter(
                logging.Formatter("%(asctime)s [%(levelname)s] %(message)s")
            )
            self._logger.addHandler(handler)
            self._stream_handler = handler

    def disable_output(self) -> None:
        if self._stream_handler is not None:
            self._logger.removeHandler(self._stream_handler)
            self._stream_handler = None

    def add_file_handler(self, logdir: str = "./.log") -> None:
        if self._file_handler is None:
            os.makedirs(logdir, exist_ok=True)
            handler = logging.FileHandler(os.path.join(logdir, "chemkin_service.log"))
            handler.setFormatter(
                logging.Formatter("%(asctime)s [%(levelname)s] %(message)s")
            )
            self._logger.addHandler(handler)
            self._file_handler = handler

    # -- passthroughs --------------------------------------------------------
    def debug(self, msg, *args, **kwargs):
        self._logger.debug(msg, *args, **kwargs)

    def info(self, msg, *args, **kwargs):
        self._logger.info(msg, *args, **kwargs)

    def warning(self, msg, *args, **kwargs):
        self._logger.warning(msg, *args, **kwargs)

    def error(self, msg, *args, **kwargs):
        self._logger.error(msg, *args, **kwargs)

    def critical(self, msg, *args, **kwargs):
        self._logger.critical(msg, *args, **kwargs)


#: module-level singleton, mirroring ``from ansys.chemkin.logger import logger``
logger = ChemkinLogger()


def get_logger():
    """The singleton logger instance (reference logger.py get_logger)."""
    return logger
