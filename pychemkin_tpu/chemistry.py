"""Chemistry set — mechanism management with the reference's API surface.

TPU-native re-implementation of the reference's ``Chemistry`` class
(reference: src/ansys/chemkin/chemistry.py:268-1822). Where the reference
wraps a single mutable native workspace (preprocessing writes linking
files, a module registry tracks the "active" chemistry set, and every
property query is a ctypes call), here ``preprocess()`` runs the pure-
Python CHEMKIN parser and the result is an immutable
:class:`~pychemkin_tpu.mechanism.MechanismRecord` pytree on ``self.mech``.
Mechanisms are values: many can coexist, none is "active", and the
save/activate registry functions are kept only as cheap parity shims
(reference: chemistry.py:46-51, 156-266, 1782-1822).

Property queries evaluate the JAX kernels in :mod:`pychemkin_tpu.ops` and
return NumPy arrays at the API boundary, matching the reference's
CGS units throughout (erg, g, mol, K, cm).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .logger import logger
from .mechanism import MechanismRecord, load_mechanism
from .ops import realgas, thermo, transport

# ---------------------------------------------------------------------------
# module-level verbosity + registry (parity with reference chemistry.py:46-51)

_verbose = False
#: registry of preprocessed chemistry sets, chemID -> Chemistry
_chemset_registry: dict[int, "Chemistry"] = {}
_chemset_init_flags: dict[int, bool] = {}
_next_chem_id = [0]


def verbose() -> bool:
    """Whether verbose printing is on (reference: chemistry.py:58)."""
    return _verbose


def set_verbose(OnOff: bool):
    """Toggle verbose printing (reference: chemistry.py:71)."""
    global _verbose
    _verbose = bool(OnOff)


def chemkin_version() -> int:
    """Version tag of the (TPU-native) solver core.

    The reference returns the Ansys release of the loaded native library
    (reference: chemistry.py:84); this build has no native library, so it
    reports a constant >= the minimum the reference's env test checks
    (tests/test_pychemkin_env.py requires >= 252)."""
    return 261


def verify_version(min_version: int) -> bool:
    """Check the solver core is at least ``min_version``
    (reference: chemistry.py:96)."""
    return chemkin_version() >= min_version


def done():
    """Release all chemistry sets (reference: chemistry.py:126 calls
    KINFinish and releases the license; here it just clears the
    registry)."""
    _chemset_registry.clear()


def chemistryset_new(chem_index: int):
    """Mark a chemistry set as freshly preprocessed / not yet
    initialized (reference: chemistry.py:222 — there it clears a
    module-level native-init flag; mechanisms are values here, so only
    the flag bookkeeping remains)."""
    _chemset_init_flags[chem_index] = False


def chemistryset_initialized(chem_index: int):
    """Flag a chemistry set's solver workspace as initialized
    (reference: chemistry.py:236)."""
    _chemset_init_flags[chem_index] = True


def check_chemistryset(chem_index: int) -> bool:
    """True if ``chem_index`` refers to a registered chemistry set
    (reference: chemistry.py:156)."""
    return chem_index in _chemset_registry


def activate_chemistryset(chem_index: int) -> int:
    """Parity shim for the reference's workspace switch
    (reference: chemistry.py:175). Mechanisms are values here, so
    activation is a no-op; returns 0 on success, 1 if unknown."""
    return 0 if chem_index in _chemset_registry else 1


def force_activate_chemistryset(chem_index: int):
    """Parity shim (reference: chemistry.py:206)."""
    if chem_index not in _chemset_registry:
        raise ValueError(f"unknown chemistry set index {chem_index}")


def check_active_chemistryset(chem_index: int) -> bool:
    """All registered sets are permanently 'active' in this build
    (reference: chemistry.py:250)."""
    return chem_index in _chemset_registry


def get_chemistryset(chem_index: int) -> "Chemistry":
    """Look up a registered Chemistry by its chemID."""
    return _chemset_registry[chem_index]


class Chemistry:
    """A preprocessed chemical mechanism: elements, species, reactions,
    thermodynamic and (optionally) transport data.

    Mirrors the reference's constructor signature (chemistry.py:283):
    file paths for the gas mechanism, surface mechanism, thermo data and
    transport data plus a label. Surface chemistry is not supported (the
    reference snapshot ships no surface-reactor models either)."""

    def __init__(self, chem: str = "", surf: str = "", therm: str = "",
                 tran: str = "", label: str = ""):
        self._chem_file = chem
        self._surf_file = surf
        self._therm_file = therm
        self._tran_file = tran
        self.label = label if label else " "
        self._chemset_index = -1
        self.mech: Optional[MechanismRecord] = None
        self.userealgas = False
        self._EOS = 0
        self._realgas_eos = realgas.PR       # default model when enabled
        self._realgas_mixing_rule = realgas.MIX_VDW
        self._critical_overrides = {}
        self._critical_cache = None
        self._want_transport = bool(tran)
        if surf and os.path.isfile(surf):
            logger.warning("surface mechanisms are not supported; "
                           "ignoring %s", surf)

    # --- file-name plumbing (reference: chemistry.py:353-594) --------------
    @property
    def chemfile(self) -> str:
        return self._chem_file

    @chemfile.setter
    def chemfile(self, filename: str):
        self._chem_file = filename

    @property
    def thermfile(self) -> str:
        return self._therm_file

    @thermfile.setter
    def thermfile(self, filename: str):
        self._therm_file = filename

    @property
    def tranfile(self) -> str:
        return self._tran_file

    @tranfile.setter
    def tranfile(self, filename: str):
        self._tran_file = filename

    @property
    def surffile(self) -> str:
        return self._surf_file

    @surffile.setter
    def surffile(self, filename: str):
        self._surf_file = filename

    def set_file_names(self, chem: str = "", surf: str = "", therm: str = "",
                       tran: str = ""):
        """Set any of the mechanism input file paths
        (reference: chemistry.py:526)."""
        if chem:
            self._chem_file = chem
        if surf:
            self._surf_file = surf
        if therm:
            self._therm_file = therm
        if tran:
            self._tran_file = tran

    # --- preprocessing (reference: chemistry.py:595-753) -------------------
    def preprocess(self) -> int:
        """Parse the mechanism files into a :class:`MechanismRecord`.

        The reference shells into the native preprocessor, writes linking
        files, and registers the workspace (chemistry.py:595-732); here the
        pure-Python parser produces the pytree directly. Returns 0 on
        success (raises on parse errors — the rebuild replaces the
        reference's ``exit()`` error style with exceptions)."""
        if not self._chem_file:
            raise ValueError("no mechanism input file given")
        self.mech = load_mechanism(
            self._chem_file,
            thermo_path=self._therm_file or None,
            transport_path=self._tran_file or None,
        )
        self._chemset_index = _next_chem_id[0]
        _next_chem_id[0] += 1
        _chemset_registry[self._chemset_index] = self
        if _verbose:
            print(f"preprocessed mechanism: {self.KK} species, "
                  f"{self.IIGas} gas reactions, {self.MM} elements")
        return 0

    @classmethod
    def from_mechanism(cls, mech: MechanismRecord,
                       label: str = "") -> "Chemistry":
        """Wrap an already-parsed :class:`MechanismRecord` (no reference
        analog — the TPU-native path for embedded/test fixtures)."""
        obj = cls(label=label)
        obj.mech = mech
        obj._chemset_index = _next_chem_id[0]
        _next_chem_id[0] += 1
        _chemset_registry[obj._chemset_index] = obj
        return obj

    def _require_mech(self) -> MechanismRecord:
        if self.mech is None:
            raise RuntimeError("chemistry set has not been preprocessed; "
                               "call preprocess() first")
        return self.mech

    def verify_transport_data(self) -> bool:
        """Whether transport data is available
        (reference: chemistry.py:794)."""
        return self.mech is not None and self.mech.has_transport

    def verify_surface_mechanism(self) -> bool:
        """Surface chemistry is unsupported (reference: chemistry.py:809)."""
        return False

    # --- sizes and symbols (reference: chemistry.py:824-1068) --------------
    @property
    def chemID(self) -> int:
        """Registry index of this chemistry set
        (reference: chemistry.py:919)."""
        return self._chemset_index

    @property
    def surfchem(self) -> int:
        return 0

    @property
    def KK(self) -> int:
        """Number of gas species (reference: chemistry.py:948)."""
        return self._require_mech().n_species

    @property
    def MM(self) -> int:
        """Number of elements (reference: chemistry.py:963)."""
        return self._require_mech().n_elements

    @property
    def IIGas(self) -> int:
        """Number of gas-phase reactions (reference: chemistry.py:978)."""
        return self._require_mech().n_reactions

    @property
    def species_symbols(self) -> list:
        """Gas species symbols (reference: chemistry.py:824)."""
        return list(self._require_mech().species_names)

    @property
    def element_symbols(self) -> list:
        """Element symbols (reference: chemistry.py:864)."""
        return list(self._require_mech().element_names)

    def get_specindex(self, specname: str) -> int:
        """Species index by symbol, -1 if absent (case-insensitive;
        reference: chemistry.py:902)."""
        try:
            return self._require_mech().species_index(specname)
        except KeyError:
            return -1

    @property
    def AWT(self) -> np.ndarray:
        """Atomic weights [MM], g/mol (reference: chemistry.py:993)."""
        return np.asarray(self._require_mech().awt)

    @property
    def WT(self) -> np.ndarray:
        """Species molecular weights [KK], g/mol
        (reference: chemistry.py:1030)."""
        return np.asarray(self._require_mech().wt)

    # --- species thermodynamic properties (chemistry.py:1069-1314) ---------
    # The reference returns MOLAR units from these (it converts the native
    # library's mass-based values by multiplying with WT — chemistry.py:1124
    # "convert [ergs/g-K] to [ergs/mol-K]"). The mass-based kernels stay
    # internal (ops.thermo); the API boundary is molar.
    def SpeciesCp(self, temp: float) -> np.ndarray:
        """Species specific heats Cp [KK] at ``temp``, erg/(mol K)
        (reference: chemistry.py:1069, molar conversion :1124)."""
        mech = self._require_mech()
        return np.asarray(thermo.species_cp_mass(mech, float(temp))) \
            * np.asarray(mech.wt)

    def SpeciesCv(self, temp: float) -> np.ndarray:
        """Species Cv [KK], erg/(mol K) (reference: chemistry.py:1137)."""
        mech = self._require_mech()
        return np.asarray(thermo.species_cv_mass(mech, float(temp))) \
            * np.asarray(mech.wt)

    def SpeciesH(self, temp: float) -> np.ndarray:
        """Species enthalpies [KK], erg/mol
        (reference: chemistry.py:1176)."""
        mech = self._require_mech()
        return np.asarray(thermo.species_enthalpy_mass(mech, float(temp))) \
            * np.asarray(mech.wt)

    def SpeciesU(self, temp: float) -> np.ndarray:
        """Species internal energies [KK], erg/mol
        (reference: chemistry.py:1243)."""
        mech = self._require_mech()
        return np.asarray(
            thermo.species_internal_energy_mass(mech, float(temp))) \
            * np.asarray(mech.wt)

    # --- species transport properties (chemistry.py:1316-1471) -------------
    def _require_transport(self) -> MechanismRecord:
        mech = self._require_mech()
        if not mech.has_transport:
            raise RuntimeError("mechanism has no transport data; provide a "
                               "tran file (reference: chemistry.py:1336)")
        return mech

    def SpeciesVisc(self, temp: float = 0.0) -> np.ndarray:
        """Pure-species viscosities [KK], g/(cm s)
        (reference: chemistry.py:1316)."""
        return np.asarray(
            transport.species_viscosities(self._require_transport(),
                                          float(temp)))

    def SpeciesCond(self, temp: float = 0.0) -> np.ndarray:
        """Pure-species conductivities [KK], erg/(cm K s)
        (reference: chemistry.py:1361)."""
        return np.asarray(
            transport.species_conductivities(self._require_transport(),
                                             float(temp)))

    def SpeciesDiffusionCoeffs(self, temp: float = 0.0,
                               pres: float = 0.0) -> np.ndarray:
        """Binary diffusion coefficient matrix [KK, KK], cm^2/s
        (reference: chemistry.py:1410)."""
        return np.asarray(
            transport.binary_diffusion_coefficients(
                self._require_transport(), float(temp), float(pres)))

    # --- composition matrix (chemistry.py:1472-1533) -----------------------
    def SpeciesComposition(self, elemindex: int = -1,
                           specindex: int = -1):
        """Elemental composition: full NCF matrix [KK, MM], one species row,
        one element column, or a single count, depending on which indices
        are given (reference: chemistry.py:1472)."""
        ncf = np.asarray(self._require_mech().ncf)
        if elemindex < 0 and specindex < 0:
            return ncf
        if elemindex < 0:
            return ncf[specindex]
        if specindex < 0:
            return ncf[:, elemindex]
        return ncf[specindex, elemindex]

    # --- reaction parameters (chemistry.py:1604-1781) ----------------------
    def get_reaction_parameters(self):
        """(A, beta, Ea/R) of all gas reactions; activation energies are
        returned as activation TEMPERATURES in K, matching the reference
        (reference: chemistry.py:1604)."""
        mech = self._require_mech()
        return (np.asarray(mech.A), np.asarray(mech.beta),
                np.asarray(mech.Ea_R))

    def set_reaction_AFactor(self, reaction_index: int, AFactor: float):
        """(Re)set one reaction's pre-exponential. 1-based reaction index,
        matching the reference (reference: chemistry.py:1636). Rebinds
        ``self.mech`` to a new record (records are immutable values)."""
        mech = self._require_mech()
        if reaction_index < 1 or reaction_index > mech.n_reactions:
            raise ValueError(
                f"reaction index must be in [1, {mech.n_reactions}]")
        self.mech = mech.with_A_factor(reaction_index - 1, AFactor)

    def get_gas_reaction_string(self, reaction_index: int) -> str:
        """Human-readable reaction equation, 1-based index
        (reference: chemistry.py:1726)."""
        mech = self._require_mech()
        if reaction_index < 1 or reaction_index > mech.n_reactions:
            raise ValueError(
                f"reaction index must be in [1, {mech.n_reactions}]")
        return mech.reaction_equations[reaction_index - 1]

    # --- real-gas cubic EOS (reference: chemistry.py:1535-1603) -----------
    # The reference reads the EOS selection and critical data from the
    # mechanism's native real-gas block; here critical constants come
    # from the built-in table in ops/realgas.py plus per-species user
    # overrides, and the model is selected by name/index.

    realgas_CuEOS = list(realgas.EOS_NAMES)
    realgas_mixing_rules = list(realgas.MIXING_RULE_NAMES)

    @property
    def EOS(self) -> int:
        """Number of available cubic EOS models
        (reference: chemistry.py:1524 — there it reports what the
        native library's real-gas module offers; all five are
        implemented here)."""
        return len(self.realgas_CuEOS) - 1      # minus 'ideal gas'

    def get_reaction_AFactor(self, reaction_index: int) -> float:
        """Arrhenius A-factor of one reaction, 1-based index
        (reference: chemistry.py:1680)."""
        mech = self._require_mech()
        if not 1 <= reaction_index <= mech.n_reactions:
            raise ValueError(
                f"reaction index must be in [1, {mech.n_reactions}]")
        return float(np.asarray(mech.A)[reaction_index - 1])

    def preprocess_transportdata(self):
        """Ask the preprocessor to include transport data
        (reference: chemistry.py:451). Here transport parses whenever a
        ``tran`` file was given; absent one, warn exactly like the
        reference does for a mechanism without a TRANSPORT block."""
        if not self._tran_file:
            logger.warning("make sure the gas mechanism contains the "
                           "'TRANSPORT ALL' block.")
        self._want_transport = True

    @property
    def summaryfile(self) -> str:
        """Path of the preprocessing summary file
        (reference: chemistry.py:440 returns the native preprocessor's
        Summary.out; here the summary is written on access).

        Regenerated UNCONDITIONALLY via tmp+rename: chemIDs restart
        from 0 in every process, so a ``Summary_<chemID>.out`` left in
        the cwd by an earlier run may describe a DIFFERENT mechanism —
        returning it verbatim (the old behavior) served stale data. The
        atomic rename also means a concurrent reader never sees a
        half-written file."""
        mech = self._require_mech()
        path = os.path.abspath(f"Summary_{self.chemID}.out")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("pychemkin_tpu preprocessing summary\n")
            f.write(f"mechanism: {self._chem_file}\n")
            f.write(f"elements ({mech.n_elements}): "
                    + " ".join(mech.element_names) + "\n")
            f.write(f"species ({mech.n_species}): "
                    + " ".join(mech.species_names) + "\n")
            f.write(f"gas reactions: {mech.n_reactions}\n")
            f.write("transport data: "
                    + ("yes" if mech.has_transport else "no") + "\n")
        os.replace(tmp, path)
        return path

    def set_critical_properties(self, species: str, Tc: float, Pc: float,
                                omega: float):
        """Provide (or override) critical constants for ``species``:
        Tc [K], Pc [bar], acentric factor."""
        self._critical_overrides[species.upper()] = (Tc, Pc, omega)
        self._critical_cache = None

    def critical_set(self):
        """Per-species critical data aligned to this mechanism."""
        if self._critical_cache is None:
            mech = self._require_mech()
            self._critical_cache = realgas.critical_set_for(
                mech.species_names, self._critical_overrides)
        return self._critical_cache

    def set_realgas_eos_model(self, model):
        """Select the cubic EOS by index 1-5 or name from
        ``Chemistry.realgas_CuEOS`` (reference selects it from the
        mechanism's real-gas data block)."""
        if isinstance(model, str):
            names = [n.lower() for n in self.realgas_CuEOS]
            model = names.index(model.lower())
        if not 1 <= int(model) <= 5:
            raise ValueError("EOS model index must be 1..5 "
                             f"({self.realgas_CuEOS[1:]})")
        self._realgas_eos = int(model)

    def use_realgas_cubicEOS(self):
        """Turn ON the real-gas cubic EOS for mixture properties
        (reference: chemistry.py:1535). Requires critical data for at
        least one species; species without data contribute ideally."""
        mech = self._require_mech()
        with_data = realgas.species_with_data(mech.species_names,
                                              self._critical_overrides)
        if not with_data:
            logger.info("mechanism is for ideal gas law only.")
            self.userealgas = False
            return
        missing = [s for s in mech.species_names if s not in with_data]
        if missing:
            logger.info("no critical data for %s; they contribute "
                        "ideally", ", ".join(missing[:8]))
        logger.info("real-gas cubic EOS model %s is turned ON.",
                    self.realgas_CuEOS[self._realgas_eos])
        self.userealgas = True

    def use_idealgas_law(self):
        """Back to the ideal-gas law (reference: chemistry.py:1573)."""
        self.userealgas = False

    def set_realgas_mixing_rule(self, rule: int = 0):
        """0 = Van der Waals, 1 = pseudocritical
        (reference: mixture.py:2737)."""
        if rule not in (0, 1):
            raise ValueError("mixing rule must be 0 (Van der Waals) or "
                             "1 (pseudocritical)")
        self._realgas_mixing_rule = int(rule)

    def verify_realgas_model(self):
        return self._realgas_eos if self.userealgas else 0

    # --- registry shims (chemistry.py:1782-1822) ---------------------------
    def save(self):
        """No-op parity shim: records are values, nothing to save
        (reference: chemistry.py:1782)."""

    def activate(self):
        """No-op parity shim (reference: chemistry.py:1805)."""
