"""Neural surrogate fast path: learned answers for the hot request
kinds, with the hard guarantee that **no unverified surrogate answer
ever leaves the server**.

The stiff-ODE DNN line (arXiv:2104.01914) shows small learned
surrogates can replace the stiff integrator for well-trodden request
regions at a fraction of the cost. This package supplies the four
pieces, each riding an existing production-spine subsystem:

- :mod:`.dataset` — sample (T, P, composition) boxes and label them by
  running the REAL solvers under the durable sweep driver:
  generation is checkpointed, resumable after SIGKILL, and banked as
  signed npz shards (the training-data flywheel).
- :mod:`.model` / :mod:`.train` — dependency-free JAX MLP ensembles
  (plain-pytree params, npz serialization, handwritten Adam);
  ``tools/train_surrogate.py`` is the CLI.
- :mod:`.verify` — per-kind cheap acceptance gates (equilibrium:
  element-potential/Gibbs residual of the predicted state; ignition:
  in-domain bound + ensemble-disagreement trust interval). The gate's
  boolean mask is the ONLY thing standing between a prediction and
  the client.
- :class:`pychemkin_tpu.serve.engines.SurrogateEngine` — serves the
  model as a new engine kind; verified hits answer directly, misses
  re-enqueue to the wrapped real engine through the existing rescue
  hand-off (``SolveStatus.SURROGATE_MISS`` as data), so a miss costs
  one extra batch window — never a wrong answer.
"""

from .dataset import (
    DatasetSignatureError,
    SampleBox,
    generate_dataset,
    load_shard,
    load_shards,
    mech_signature,
    phi_composition,
    problem_signature,
    sample_inputs,
    save_shard,
)
from .model import (
    PSR_T_SCALE,
    SurrogateModel,
    features,
    init_mlp,
    load_model,
    mlp_apply,
    model_params,
    predict,
    predict_params,
    psr_features,
    save_model,
)
from .train import fit_surrogate, train_member, training_curve_artifact
from .verify import (
    DomainBox,
    GateConfig,
    equilibrium_gate,
    equilibrium_residual,
    gate_config,
    ignition_gate,
    in_domain,
    psr_gate,
    psr_residual,
)

__all__ = [
    "DatasetSignatureError",
    "DomainBox",
    "GateConfig",
    "PSR_T_SCALE",
    "SampleBox",
    "SurrogateModel",
    "equilibrium_gate",
    "equilibrium_residual",
    "features",
    "fit_surrogate",
    "gate_config",
    "generate_dataset",
    "ignition_gate",
    "in_domain",
    "init_mlp",
    "load_model",
    "load_shard",
    "load_shards",
    "mech_signature",
    "mlp_apply",
    "model_params",
    "phi_composition",
    "predict",
    "predict_params",
    "problem_signature",
    "psr_features",
    "psr_gate",
    "psr_residual",
    "sample_inputs",
    "save_model",
    "save_shard",
    "train_member",
    "training_curve_artifact",
]
