"""Dependency-free JAX MLP surrogates: params as a plain pytree, npz
serialization, shared feature construction.

The model layer is deliberately tiny — no flax/optax/haiku (the
container bakes in jax only, and a serving hot path wants zero extra
import weight): a member's parameters are a list of ``(W, b)`` pairs,
an ensemble is a tuple of members, and the whole
:class:`SurrogateModel` (members + normalization + trained-domain box
+ problem signatures) round-trips through ONE flat ``.npz`` file with
the same tmp+``os.replace`` atomicity as every other banked artifact.

Two signatures ride inside the model and make staleness loud instead
of silent:

- ``sig``      the DATASET problem signature
  (:func:`pychemkin_tpu.surrogate.dataset.problem_signature`): what
  inputs/solver configuration produced the labels.
- ``mech_sig`` the mechanism-only identity
  (:func:`~pychemkin_tpu.surrogate.dataset.mech_signature`): the
  serving layer refuses to attach a surrogate trained against a
  different mechanism (see
  :class:`pychemkin_tpu.serve.engines.SurrogateEngine`).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import atomic_savez

#: model-file layout version; bump on incompatible key changes
MODEL_VERSION = 1

#: floor under mass fractions before the log-concentration features —
#: species absent from a mixture must map to a FINITE feature value
Y_FLOOR = 1e-12

#: floor under predicted mole fractions (matches the equilibrium
#: kernel's numerically-absent convention)
X_FLOOR = 1e-30


class Normalization(NamedTuple):
    """Feature/target whitening captured at fit time (std floored so a
    constant feature — e.g. a fixed-composition dataset's inert
    species column — normalizes to zero instead of dividing by 0)."""
    x_mean: Any
    x_std: Any
    y_mean: Any
    y_std: Any


class SurrogateModel(NamedTuple):
    """A trained ensemble plus everything serving needs to trust it."""
    kind: str                       # base request kind ("ignition", ...)
    members: Tuple[Any, ...]        # ensemble: each a [(W, b), ...] list
    norm: Normalization
    lo: Any                         # [F] per-feature trained-domain min
    hi: Any                         # [F] per-feature trained-domain max
    sig: str                        # dataset problem signature
    mech_sig: str                   # mechanism-only identity
    meta: Dict[str, Any]            # extra static facts (option, t_end…)


#: temperature scale of the PSR surrogate's first target component
#: (T/PSR_T_SCALE keeps it O(1) next to the ln-mass-fraction columns)
PSR_T_SCALE = 1.0e3


def features(T, P, Y):
    """The shared surrogate feature map for (T, P, composition) boxes:
    ``[1000/T, log10 P, log10 Y_k...]`` — Arrhenius-like inverse
    temperature plus log-pressure plus LOG-concentration inputs (the
    stiff-ODE DNN line's representation; arXiv:2104.01914), so targets
    that span decades see near-linear structure. Batched over the
    leading axis; ``Y`` is ``[..., KK]`` mass fractions."""
    T = jnp.asarray(T, jnp.float64)
    P = jnp.asarray(P, jnp.float64)
    Y = jnp.asarray(Y, jnp.float64)
    cols = [1000.0 / T, jnp.log10(P)]
    logY = jnp.log10(jnp.maximum(Y, Y_FLOOR))
    return jnp.concatenate(
        [jnp.stack(cols, axis=-1), logY], axis=-1)


def psr_features(tau, P, Y_in, h_in):
    """Feature map of the PSR-state surrogate: ``[log10 tau, log10 P,
    1e-10 * h_in, log10 Y_in_k...]``. Residence time and pressure span
    decades (log); inlet enthalpy is near-linear in inlet temperature
    so a fixed rescale keeps it O(1); inlet composition rides the same
    log-concentration representation as :func:`features`. Batched over
    the leading axis; ``Y_in`` is ``[..., KK]`` mass fractions."""
    tau = jnp.asarray(tau, jnp.float64)
    P = jnp.asarray(P, jnp.float64)
    h_in = jnp.asarray(h_in, jnp.float64)
    Y_in = jnp.asarray(Y_in, jnp.float64)
    cols = [jnp.log10(jnp.maximum(tau, 1e-30)), jnp.log10(P),
            1e-10 * h_in]
    logY = jnp.log10(jnp.maximum(Y_in, Y_FLOOR))
    return jnp.concatenate(
        [jnp.stack(cols, axis=-1), logY], axis=-1)


def init_mlp(key, sizes: Sequence[int]) -> List[Tuple[Any, Any]]:
    """Glorot-initialized MLP parameters for layer widths ``sizes``
    (``[n_in, hidden..., n_out]``)."""
    sizes = [int(s) for s in sizes]
    if len(sizes) < 2:
        raise ValueError(f"need at least in/out sizes, got {sizes}")
    params = []
    for n_in, n_out in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / (n_in + n_out))
        W = scale * jax.random.normal(sub, (n_in, n_out), jnp.float64)
        params.append((W, jnp.zeros((n_out,), jnp.float64)))
    return params


def mlp_apply(params, x):
    """Forward pass: tanh hidden layers, linear output. ``x`` is
    ``[..., F]`` (already normalized)."""
    for W, b in params[:-1]:
        x = jnp.tanh(x @ W + b)
    W, b = params[-1]
    return x @ W + b


def model_params(model: SurrogateModel):
    """The model's numeric leaves as one pytree ``(members, norm, lo,
    hi)`` — everything :func:`predict` and the domain gates read.
    Serving passes this as a RUNTIME argument to its jitted batch
    functions instead of closing over the model, so swapping weights
    of the same architecture (a flywheel promotion, a shadow
    candidate) reuses the already-compiled program: zero new XLA
    compiles on the hot path."""
    return (model.members, model.norm,
            jnp.asarray(model.lo), jnp.asarray(model.hi))


def predict_params(members, norm: Normalization, feats):
    """:func:`predict` against bare param leaves (the jit-traceable
    form — see :func:`model_params`)."""
    xn = (feats - norm.x_mean) / norm.x_std
    preds = jnp.stack([mlp_apply(m, xn) for m in members])
    return preds * norm.y_std + norm.y_mean


def predict(model: SurrogateModel, feats):
    """Every ensemble member's denormalized prediction for raw
    features ``feats`` ``[..., F]``; returns ``[M, ..., O]``. The
    caller takes the mean as the answer and the spread as the
    trust/disagreement signal (:mod:`.verify`)."""
    return predict_params(model.members, model.norm, feats)


def layer_sizes(member) -> List[int]:
    """Recover ``[n_in, hidden..., n_out]`` from one member's params."""
    return [int(member[0][0].shape[0])] + [int(W.shape[1])
                                           for W, _ in member]


# ---------------------------------------------------------------------------
# npz serialization (flat keys; tmp + os.replace atomicity)

def _meta_items(meta: Dict[str, Any]):
    # meta values are scalars/strings only — enough for option ids,
    # protocol constants; anything array-shaped belongs in the dataset
    for k, v in sorted(meta.items()):
        if isinstance(v, (str, int, float, bool)) or v is None:
            yield k, v
        else:
            raise TypeError(
                f"model meta value {k!r} must be a scalar, got "
                f"{type(v).__name__}")


def save_model(path: str, model: SurrogateModel) -> None:
    """Atomically write the whole model to one ``.npz``."""
    payload: Dict[str, np.ndarray] = {
        "v": np.asarray(MODEL_VERSION),
        "kind": np.asarray(model.kind),
        "sig": np.asarray(model.sig),
        "mech_sig": np.asarray(model.mech_sig),
        "x_mean": np.asarray(model.norm.x_mean),
        "x_std": np.asarray(model.norm.x_std),
        "y_mean": np.asarray(model.norm.y_mean),
        "y_std": np.asarray(model.norm.y_std),
        "lo": np.asarray(model.lo),
        "hi": np.asarray(model.hi),
        "n_members": np.asarray(len(model.members)),
    }
    for mi, member in enumerate(model.members):
        payload[f"m{mi}_n_layers"] = np.asarray(len(member))
        for li, (W, b) in enumerate(member):
            payload[f"m{mi}_W{li}"] = np.asarray(W)
            payload[f"m{mi}_b{li}"] = np.asarray(b)
    for k, v in _meta_items(model.meta):
        payload[f"meta_{k}"] = np.asarray("" if v is None else v)
    atomic_savez(path, **payload)


def _meta_value(raw: str):
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    if raw in ("True", "False"):
        return raw == "True"
    return raw or None


def load_model(path: str) -> SurrogateModel:
    """Load a model written by :func:`save_model`. Unlike checkpoint
    manifests, a surrogate model is NOT an optimization — a torn or
    wrong-version file raises (serving must fail loudly rather than
    answer from a half-loaded net)."""
    with np.load(path, allow_pickle=False) as f:
        if int(f["v"]) != MODEL_VERSION:
            raise ValueError(
                f"surrogate model {path} has layout version "
                f"{int(f['v'])}, expected {MODEL_VERSION}")
        members = []
        for mi in range(int(f["n_members"])):
            member = []
            for li in range(int(f[f"m{mi}_n_layers"])):
                member.append((jnp.asarray(f[f"m{mi}_W{li}"]),
                               jnp.asarray(f[f"m{mi}_b{li}"])))
            members.append(member)
        meta = {k[len("meta_"):]: _meta_value(str(f[k]))
                for k in f.files if k.startswith("meta_")}
        return SurrogateModel(
            kind=str(f["kind"]), members=tuple(members),
            norm=Normalization(
                x_mean=jnp.asarray(f["x_mean"]),
                x_std=jnp.asarray(f["x_std"]),
                y_mean=jnp.asarray(f["y_mean"]),
                y_std=jnp.asarray(f["y_std"])),
            lo=jnp.asarray(f["lo"]), hi=jnp.asarray(f["hi"]),
            sig=str(f["sig"]), mech_sig=str(f["mech_sig"]), meta=meta)
