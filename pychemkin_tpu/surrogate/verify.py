"""Per-kind surrogate acceptance gates: the ONLY thing standing
between a neural prediction and the client.

The serving contract ("statistically fast, never wrong") rests on the
asymmetry these gates exploit: *verifying* a candidate answer is far
cheaper than *computing* one. Each gate returns a boolean mask per
batch element — verified lanes answer directly, everything else is
NaN-masked and falls through to the real solver.

- **equilibrium** — physics check on the PREDICTED state, reusing the
  element-potential formulation of
  :mod:`pychemkin_tpu.ops.equilibrium`: at equilibrium the
  dimensionless chemical potentials ``mu_k/RT = g_k/RT + ln x_k +
  ln(P/Patm)`` lie exactly in the row space of the element matrix
  (``mu = ncf @ lam`` — the condition the real Newton drives to zero),
  and the predicted composition must conserve the inlet's element
  moles. The gate is the abundance-weighted residual of both, one
  weighted least-squares per element — O(KK·MM²) against the solver's
  80 Newton iterations of Jacobian + solve.
- **ignition delay** — no cheap physics residual exists for a scalar
  delay, so the gate is epistemic: the input must lie inside the
  TRAINED feature box (in-domain bound), the ensemble members must
  agree (trust-interval disagreement in log10-time), and the predicted
  delay must fit inside the request's integration horizon (a real
  solve would otherwise report "not ignited", which the surrogate
  cannot).

Environment knobs (read when a gate config is built — engine
construction time; explicit kwargs win):

- ``PYCHEMKIN_SURROGATE_DOMAIN_MARGIN``  fraction of each feature's
  trained span allowed OUTSIDE the box (default 0.0: strict).
- ``PYCHEMKIN_SURROGATE_IGN_DISAGREE``   max ensemble std of
  log10(delay/s) (default 0.1 ≈ ±26 %).
- ``PYCHEMKIN_SURROGATE_IGN_TEND_FRAC``  predicted delay must be below
  this fraction of the request's ``t_end`` (default 0.8).
- ``PYCHEMKIN_SURROGATE_EQ_RESID``       max equilibrium
  element-potential/element-balance residual (default 0.05).
- ``PYCHEMKIN_SURROGATE_PSR_RESID``      max tau-scaled PSR
  steady-state residual of the predicted reactor state (default
  0.05).

The **PSR** gate mirrors the equilibrium one in spirit: plugging the
predicted ``(T, Y)`` into the reactor's own steady-state equations
(:func:`pychemkin_tpu.ops.psr.make_rhs`, tau mode) and scaling by the
residence time yields an O(1) mass/energy-imbalance fraction — one
RHS evaluation against the real solver's damped-Newton + pseudo-
transient march.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax.numpy as jnp

from .. import knobs
from ..constants import P_ATM
from ..ops import linalg, thermo

_TINY = 1e-30


class GateConfig(NamedTuple):
    """Resolved gate thresholds (env defaults frozen at engine build —
    a compiled program bakes them in; rebuild the engine to re-read)."""
    domain_margin: float = 0.0
    ign_disagree_max: float = 0.1
    ign_t_end_frac: float = 0.8
    eq_resid_max: float = 0.05
    psr_resid_max: float = 0.05


class DomainBox(NamedTuple):
    """The trained-domain corner the gates read (``.lo``/``.hi`` duck-
    typed like :class:`~pychemkin_tpu.surrogate.model.SurrogateModel`).
    Serving builds one from its runtime param pytree so the gates see
    TRACED bounds — a promoted model's grown box needs no recompile."""
    lo: Any
    hi: Any


def gate_config(*, domain_margin: Optional[float] = None,
                ign_disagree_max: Optional[float] = None,
                ign_t_end_frac: Optional[float] = None,
                eq_resid_max: Optional[float] = None,
                psr_resid_max: Optional[float] = None) -> GateConfig:
    """Thresholds from explicit kwargs, else env, else the registry
    defaults (pychemkin_tpu.knobs owns default + parse semantics)."""
    def pick(val, env):
        return float(val) if val is not None else knobs.value(env)

    return GateConfig(
        domain_margin=pick(domain_margin,
                           "PYCHEMKIN_SURROGATE_DOMAIN_MARGIN"),
        ign_disagree_max=pick(ign_disagree_max,
                              "PYCHEMKIN_SURROGATE_IGN_DISAGREE"),
        ign_t_end_frac=pick(ign_t_end_frac,
                            "PYCHEMKIN_SURROGATE_IGN_TEND_FRAC"),
        eq_resid_max=pick(eq_resid_max,
                          "PYCHEMKIN_SURROGATE_EQ_RESID"),
        psr_resid_max=pick(psr_resid_max,
                           "PYCHEMKIN_SURROGATE_PSR_RESID"))


def in_domain(lo, hi, feats, margin: float = 0.0):
    """Per-element mask: every feature inside the trained box,
    stretched by ``margin`` × its span on each side. Batched over the
    leading axis of ``feats`` [..., F]."""
    span = jnp.maximum(hi - lo, _TINY)
    ok = ((feats >= lo - margin * span)
          & (feats <= hi + margin * span))
    return jnp.all(ok, axis=-1)


def ignition_gate(model, feats, preds_log10, t_end, cfg: GateConfig):
    """The ignition acceptance mask. ``preds_log10`` is the ensemble's
    per-member log10(delay/s) predictions ``[M, B]``; returns
    ``(verified [B], disagreement [B])`` — disagreement is the
    ensemble std in log10 units, the value the serving layer records
    in the residual histogram."""
    disagree = jnp.std(preds_log10, axis=0)
    mean_log10 = jnp.mean(preds_log10, axis=0)
    t_pred = 10.0 ** mean_log10
    ok = (in_domain(model.lo, model.hi, feats, cfg.domain_margin)
          & (disagree <= cfg.ign_disagree_max)
          & (t_pred <= cfg.ign_t_end_frac * t_end)
          & jnp.isfinite(mean_log10))
    return ok, disagree


def equilibrium_residual(mech, T, P, X, b):
    """Element-potential + element-balance residual of ONE predicted
    equilibrium state (vmap for batches).

    ``X`` is the predicted mole-fraction vector, ``b`` the inlet's
    element moles per gram. The chemical potentials of the predicted
    state are projected onto the element matrix by abundance-weighted
    least squares (the element-potential representation the real
    solver iterates on); the residual combines the weighted rms of
    what the projection cannot explain with the scaled element-balance
    error of the predicted composition."""
    MM = mech.ncf.shape[1]
    X = jnp.maximum(X, 0.0)
    X = X / jnp.maximum(jnp.sum(X), _TINY)
    g = thermo.g_RT(mech, T)
    mu = g + jnp.log(jnp.maximum(X, _TINY)) + jnp.log(
        jnp.maximum(P, _TINY) / P_ATM)
    # abundance weights: trace species carry log-floor noise, the
    # Gibbs condition is only meaningful where moles actually are
    W = jnp.maximum(X, 1e-6)
    A = mech.ncf
    AtWA = A.T @ (W[:, None] * A) + 1e-10 * jnp.eye(MM)
    lam = linalg.solve(AtWA, A.T @ (W * mu))
    r = mu - A @ lam
    r_mu = jnp.sqrt(jnp.sum(W * r * r) / jnp.maximum(jnp.sum(W), _TINY))
    # element conservation: moles of each element in the predicted
    # composition (per gram) must match the inlet's
    wbar = jnp.maximum(jnp.dot(X, mech.wt), _TINY)
    b_pred = A.T @ (X / wbar)
    b_tot = jnp.maximum(jnp.sum(b), _TINY)
    b_scale = jnp.maximum(b, 1e-6 * b_tot)
    r_el = jnp.sqrt(jnp.mean(((b_pred - b) / b_scale) ** 2))
    return r_mu + r_el


def equilibrium_gate(mech, model, feats, T, P, X_pred, b,
                     cfg: GateConfig):
    """The equilibrium acceptance mask (batched): in-domain AND the
    Gibbs/element residual of the predicted state under
    :func:`equilibrium_residual` below the threshold. Returns
    ``(verified [B], residual [B])``."""
    import jax

    resid = jax.vmap(lambda t, p, x, bb: equilibrium_residual(
        mech, t, p, x, bb))(T, P, X_pred, b)
    ok = (in_domain(model.lo, model.hi, feats, cfg.domain_margin)
          & jnp.isfinite(resid) & (resid <= cfg.eq_resid_max))
    return ok, resid


def psr_residual(mech, tau, P, Y_in, h_in, T, Y, energy: str = "ENRG"):
    """Tau-scaled steady-state residual of ONE predicted PSR state
    (vmap for batches): the reactor's own transient RHS evaluated at
    the predicted ``(Y, T)``, times the residence time, so each
    component is an O(1) imbalance FRACTION (the same scaling the real
    solver's Newton drives to zero; temperature divided by
    :data:`~pychemkin_tpu.ops.psr.T_SCALE` to sit next to the mass
    fractions). Non-finite components count as a large miss instead of
    poisoning the mean."""
    from ..ops import psr as psr_ops

    zero = jnp.zeros((), jnp.float64)
    args = psr_ops.PSRArgs(
        mech=mech, P=P, Y_in=Y_in, h_in=h_in, tau=tau,
        volume=zero, mdot=zero, qloss=zero, T_fixed=zero)
    rhs = psr_ops.make_rhs(psr_ops.MODE_TAU, energy)
    y = jnp.concatenate([Y, jnp.reshape(T, (1,))])
    r = rhs(0.0, y, args) * jnp.maximum(tau, _TINY)
    r = r.at[-1].divide(psr_ops.T_SCALE)
    r = jnp.where(jnp.isfinite(r), r, 1e3)
    return jnp.sqrt(jnp.mean(r * r))


def psr_gate(mech, model, feats, tau, P, Y_in, h_in, T_pred, Y_pred,
             cfg: GateConfig, energy: str = "ENRG"):
    """The PSR acceptance mask (batched): in-domain AND the tau-scaled
    steady-state residual of the predicted state under
    :func:`psr_residual` below the threshold. Returns
    ``(verified [B], residual [B])``."""
    import jax

    resid = jax.vmap(lambda t, p, yi, hi_, T, Y: psr_residual(
        mech, t, p, yi, hi_, T, Y, energy))(
            tau, P, Y_in, h_in, T_pred, Y_pred)
    ok = (in_domain(model.lo, model.hi, feats, cfg.domain_margin)
          & jnp.isfinite(T_pred) & (T_pred > 0.0)
          & jnp.isfinite(resid) & (resid <= cfg.psr_resid_max))
    return ok, resid
