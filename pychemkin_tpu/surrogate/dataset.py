"""Surrogate training data: sampled (T, P, composition) boxes labeled
by the REAL solvers under the durable sweep driver.

Dataset generation is just another sweep, so it rides the whole PR 4
durability contract for free
(:func:`pychemkin_tpu.resilience.driver.run_vmapped_sweep_job`):
checkpoint banking per chunk, graceful SIGTERM → resumable rc 75,
retry/backoff, SIGKILL-safe resume that bit-matches an uninterrupted
run (inputs are deterministic from the seed, chunk layouts identical,
banked chunks adopted verbatim). This is the training-data flywheel:
every production sweep the driver runs is future label material.

A finished generation banks ONE npz **shard** carrying:

- ``x``/``y``    feature/target arrays (the shared feature map of
                 :func:`pychemkin_tpu.surrogate.model.features`;
                 log-time targets for ignition delay, log-mole-fraction
                 targets for equilibrium),
- ``valid``      per-row label mask (the solver's per-element
                 ``SolveStatus`` verdict — failed labels are never
                 silently trained on),
- ``sig``        the PROBLEM signature
                 (:func:`problem_signature`: mechanism + box + seed +
                 solver configuration) — a stale shard can't silently
                 train against a different mechanism: every loader
                 checks it (:func:`load_shards`) and so does the
                 serving layer at model-attach time,
- ``lo``/``hi``  the sampled box in FEATURE space (the verification
                 gate's in-domain bound; :mod:`.verify`).

Shards concatenate (:func:`load_shards`), so repeated generations over
time — different seeds, widened boxes — grow one training set as long
as their problem identity matches.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import equilibrium as eq_ops
from ..ops import reactors as reactor_ops
from ..resilience import checkpoint
from ..resilience.driver import run_vmapped_sweep_job
from .. import telemetry
from ..resilience.status import SolveStatus, status_counts
from .model import PSR_T_SCALE, X_FLOOR, features, psr_features

#: shard-file layout version; an old shard REFUSES to load (unlike a
#: checkpoint, a training shard is an input, not an optimization)
SHARD_VERSION = 1

#: the request kinds a dataset can label
KINDS = ("ignition", "equilibrium", "psr")

#: per-kind default solver configuration for labeling — the serving
#: protocol's knobs (tight enough to trust, cheap enough to sweep)
DEFAULT_SOLVER_KWARGS = {
    "ignition": {"rtol": 1e-6, "atol": 1e-10,
                 "max_steps_per_segment": 4000},
    "equilibrium": {"option": 1, "n_iter": 80},
    "psr": {"energy": "ENRG", "n_newton": 50, "n_pseudo": 100},
}


class DatasetSignatureError(RuntimeError):
    """A shard/model's problem signature does not match: the data was
    generated for a different mechanism, box, seed, or solver
    configuration. Refusing loudly is the whole point — a silently
    mismatched dataset would train a surrogate against the wrong
    chemistry."""


class SampleBox(NamedTuple):
    """The sampled (T, P, composition) box. Composition is
    parameterized by fuel/air equivalence ratio ``phi`` (H2/air for the
    h2o2/grisyn fixture family, CH4/air when the mechanism carries
    CH4), so the box stays low-dimensional while the feature map sees
    full log-concentration inputs. ``t_end`` is the ignition
    integration horizon (ignition kind only); ``tau`` the sampled
    residence-time range (psr kind only, where ``T`` is the INLET
    temperature)."""
    T: Tuple[float, float] = (1250.0, 1400.0)
    P: Tuple[float, float] = (0.9e6, 1.2e6)
    phi: Tuple[float, float] = (0.85, 1.15)
    t_end: float = 4e-4
    tau: Tuple[float, float] = (3e-4, 3e-3)


def phi_composition(mech, phi, fuel: Optional[str] = None) -> np.ndarray:
    """Mass fractions for fuel/air at equivalence ratio(s) ``phi``
    (batched). THE one place the fuel/air recipe lives —
    ``benchmarks._stoich_Y0`` (and through it the loadgen samplers)
    delegate here, so the trained feature box and the traffic the
    samplers offer can never drift apart. ``fuel`` defaults to CH4
    when the mechanism carries it (ch4global, GRI-3.0), else H2 (the
    h2o2/grisyn fixture family's live chemistry)."""
    from ..ops import thermo

    names = list(mech.species_names)
    if fuel is None:
        fuel = "CH4" if "CH4" in names else "H2"
    phi = np.atleast_1d(np.asarray(phi, np.float64))
    X = np.zeros((phi.shape[0], len(names)))
    if fuel == "CH4":
        X[:, names.index("CH4")] = phi          # CH4 + 2 O2
        X[:, names.index("O2")] = 2.0
        X[:, names.index("N2")] = 7.52
    elif fuel == "H2":
        X[:, names.index("H2")] = 2.0 * phi     # 2 H2 + O2
        X[:, names.index("O2")] = 1.0
        X[:, names.index("N2")] = 3.76
    else:
        raise ValueError(f"unknown fuel {fuel!r}; expected CH4 or H2")
    X = X / X.sum(axis=1, keepdims=True)
    return np.asarray(jax.vmap(
        lambda x: thermo.X_to_Y(mech, x))(jnp.asarray(X)))


def sample_inputs(mech, box: SampleBox, n: int,
                  seed: int) -> Dict[str, np.ndarray]:
    """Deterministic input draw: uniform T and phi, log-uniform P.
    The SAME (box, n, seed) always yields the same inputs — the
    property the driver's bit-match resume contract rests on."""
    rng = np.random.default_rng(seed)
    T = rng.uniform(*box.T, size=n)
    P = np.exp(rng.uniform(np.log(box.P[0]), np.log(box.P[1]), size=n))
    phi = rng.uniform(*box.phi, size=n)
    # tau draws LAST so the (T, P, phi) sequences of every pre-psr
    # (box, n, seed) stay bit-identical to what they were before the
    # psr kind existed — banked checkpoints keep resuming
    tau = np.exp(rng.uniform(np.log(box.tau[0]), np.log(box.tau[1]),
                             size=n))
    return {"T": T, "P": P, "phi": phi,
            "Y": phi_composition(mech, phi),
            "t_end": np.full(n, box.t_end), "tau": tau}


def mech_signature(mech) -> str:
    """Mechanism-only identity — every array leaf plus species names.
    The serve layer compares this at model-attach time so a surrogate
    can never answer for a mechanism it was not trained on."""
    return checkpoint.signature("surrogate-mech", tree=mech)


def problem_signature(mech, kind: str, box: SampleBox, n: int,
                      seed: int,
                      solver_kwargs: Optional[Dict] = None) -> str:
    """The dataset's problem identity: mechanism, kind, box, draw seed
    and size, and the labeling solver's configuration — everything that
    determines the labels, nothing about execution layout (the
    checkpoint discipline of :mod:`pychemkin_tpu.resilience`)."""
    if kind not in KINDS:
        raise ValueError(f"unknown dataset kind {kind!r}; expected one "
                         f"of {KINDS}")
    kw = dict(DEFAULT_SOLVER_KWARGS[kind])
    kw.update(solver_kwargs or {})
    return checkpoint.config_signature(
        "surrogate-dataset", kind, int(n), int(seed), tuple(box),
        cfg=kw, tree=mech)


# ---------------------------------------------------------------------------
# labeling solvers (one jitted program per job: every driver chunk is
# edge-padded to the same length, so the whole sweep is one compile)

def _ignition_index_solve(mech, inputs, kw):
    fn = jax.jit(lambda T, P, Y, te: reactor_ops.ignition_delay_sweep(
        mech, "CONP", "ENRG", T, P, Y, te, **kw))

    def index_solve(idx):
        times, ok, status = fn(
            jnp.asarray(inputs["T"][idx]), jnp.asarray(inputs["P"][idx]),
            jnp.asarray(inputs["Y"][idx]),
            jnp.asarray(inputs["t_end"][idx]))
        return {"time_s": np.asarray(times), "ok": np.asarray(ok),
                "status": np.asarray(status)}

    return index_solve, ("time_s", "ok", "status")


def _equilibrium_index_solve(mech, inputs, kw):
    option = int(kw.pop("option", 1))
    fn = jax.jit(jax.vmap(lambda T, P, Y: eq_ops.equilibrate(
        mech, T, P, Y, option=option, **kw)))

    def index_solve(idx):
        res = fn(jnp.asarray(inputs["T"][idx]),
                 jnp.asarray(inputs["P"][idx]),
                 jnp.asarray(inputs["Y"][idx]))
        return {"X_eq": np.asarray(res.X),
                "residual": np.asarray(res.residual),
                "status": np.asarray(res.status)}

    return index_solve, ("X_eq", "residual", "status")


def _psr_index_solve(mech, inputs, kw):
    from ..ops import psr as psr_ops
    from ..ops import thermo

    energy = str(kw.pop("energy", "ENRG"))
    fn = jax.jit(jax.vmap(lambda tau, P, Y, h: psr_ops.solve_psr(
        mech, psr_ops.MODE_TAU, energy, P=P, Y_in=Y, h_in=h,
        T_guess=1800.0, Y_guess=Y, tau=tau, **kw)))
    h_fn = jax.jit(jax.vmap(lambda T, Y: thermo.mixture_enthalpy_mass(
        mech, T, Y)))

    def index_solve(idx):
        Y = jnp.asarray(inputs["Y"][idx])
        h = h_fn(jnp.asarray(inputs["T"][idx]), Y)
        sol = fn(jnp.asarray(inputs["tau"][idx]),
                 jnp.asarray(inputs["P"][idx]), Y, h)
        return {"T_out": np.asarray(sol.T), "Y_out": np.asarray(sol.Y),
                "h_in": np.asarray(h),
                "converged": np.asarray(sol.converged),
                "status": np.asarray(sol.status)}

    return index_solve, ("T_out", "Y_out", "h_in", "converged",
                         "status")


def generate_dataset(mech, kind: str, *, n: int, seed: int = 0,
                     box: Optional[SampleBox] = None,
                     out_path: Optional[str] = None,
                     checkpoint_path: Optional[str] = None,
                     chunk_size: Optional[int] = None,
                     solver_kwargs: Optional[Dict] = None,
                     recorder=None, job_report: Optional[dict] = None,
                     **driver_kwargs):
    """Label ``n`` sampled conditions with the real solver under the
    durable driver; returns ``(shard, report)``.

    With ``out_path`` the shard is banked there atomically and — unless
    ``checkpoint_path`` overrides — the labeling job checkpoints to
    ``<out_path>.ck.npz``, so a SIGKILL mid-generation resumes after
    the last banked chunk and the finished shard bit-matches an
    uninterrupted run (``resume_count`` lands in the ``report``).
    Driver knobs (``max_retries``, ``reexec_argv``, ...) pass through
    ``driver_kwargs``.
    """
    box = box if box is not None else SampleBox()
    sig = problem_signature(mech, kind, box, n, seed, solver_kwargs)
    kw = dict(DEFAULT_SOLVER_KWARGS[kind])
    kw.update(solver_kwargs or {})
    inputs = sample_inputs(mech, box, n, seed)
    if checkpoint_path is None and out_path is not None:
        checkpoint_path = out_path + ".ck.npz"

    # the constraint option is a LABEL-defining knob: record it before
    # the equilibrium solver factory pops it, so it rides the shard
    # into the trained model's meta (the serve engine refuses requests
    # for any other option)
    option = int(kw.get("option", 1)) if kind == "equilibrium" else -1
    make = {"ignition": _ignition_index_solve,
            "equilibrium": _equilibrium_index_solve,
            "psr": _psr_index_solve}[kind]
    index_solve, result_keys = make(mech, inputs, kw)
    results, report = run_vmapped_sweep_job(
        index_solve, int(n), chunk_size=chunk_size,
        checkpoint_path=checkpoint_path, signature=sig,
        result_keys=result_keys, label=f"surrogate_dataset_{kind}",
        recorder=recorder, job_report=job_report, **driver_kwargs)

    shard = _build_shard(mech, kind, box, inputs, results, sig, option)
    if out_path is not None:
        save_shard(out_path, shard)
    return shard, report


def _build_shard(mech, kind, box, inputs, results, sig,
                 option: int = -1) -> Dict:
    if kind == "ignition":
        feats = np.asarray(features(inputs["T"], inputs["P"],
                                    inputs["Y"]))
        t = np.asarray(results["time_s"], np.float64)
        valid = (np.asarray(results["ok"], bool)
                 & (np.asarray(results["status"])
                    == int(SolveStatus.OK))
                 & np.isfinite(t) & (t > 0.0)
                 & (t < inputs["t_end"]))
        # log-time targets; invalid rows carry a placeholder the mask
        # excludes from every consumer
        y = np.where(valid, np.log10(np.where(valid, t, 1.0)),
                     0.0)[:, None]
    elif kind == "psr":
        T_out = np.asarray(results["T_out"], np.float64)
        Y_out = np.asarray(results["Y_out"], np.float64)
        h_in = np.asarray(results["h_in"], np.float64)
        feats = np.asarray(psr_features(
            inputs["tau"], inputs["P"], inputs["Y"], h_in))
        valid = ((np.asarray(results["status"])
                  == int(SolveStatus.OK))
                 & np.asarray(results["converged"], bool)
                 & np.isfinite(T_out) & (T_out > 0.0)
                 & np.all(np.isfinite(Y_out), axis=1))
        # reactor-state targets: scaled exit temperature next to
        # log-mass-fractions (same decades-spanning treatment as the
        # equilibrium targets)
        y = np.concatenate(
            [(T_out / PSR_T_SCALE)[:, None],
             np.log(np.maximum(Y_out, X_FLOOR))], axis=1)
    else:
        feats = np.asarray(features(inputs["T"], inputs["P"],
                                    inputs["Y"]))
        X_eq = np.asarray(results["X_eq"], np.float64)
        valid = (np.asarray(results["status"])
                 == int(SolveStatus.OK)) & np.all(
                     np.isfinite(X_eq), axis=1)
        y = np.log(np.maximum(X_eq, X_FLOOR))
    # the trained-domain box in FEATURE space: what verify.in_domain
    # gates against — evaluated at the SAMPLED box's corners (every
    # feature is monotone in each of T, P, phi — and tau, h_in for the
    # psr map), not the draw's min/max, so a small shard doesn't
    # understate its coverage
    if kind == "psr":
        from ..ops import thermo

        ctau, cP, cT, cphi = (g.ravel() for g in np.meshgrid(
            np.asarray(box.tau), np.asarray(box.P),
            np.asarray(box.T), np.asarray(box.phi)))
        cY = phi_composition(mech, cphi)
        ch = np.asarray(jax.vmap(
            lambda t, yy: thermo.mixture_enthalpy_mass(mech, t, yy))(
                jnp.asarray(cT), jnp.asarray(cY)))
        corner_feats = np.asarray(psr_features(ctau, cP, cY, ch))
    else:
        cT, cP, cphi = (g.ravel() for g in np.meshgrid(
            np.asarray(box.T), np.asarray(box.P), np.asarray(box.phi)))
        corner_feats = np.asarray(
            features(cT, cP, phi_composition(mech, cphi)))
    lo = corner_feats.min(axis=0)
    hi = corner_feats.max(axis=0)
    return {
        "v": SHARD_VERSION, "kind": kind, "sig": sig,
        "mech_sig": mech_signature(mech),
        "x": feats, "y": y, "valid": valid,
        "lo": lo, "hi": hi,
        "t_end": float(box.t_end),
        "option": int(option),        # -1 = not an equilibrium shard
        "status_counts": status_counts(results["status"]),
    }


def save_shard(path: str, shard: Dict) -> None:
    """Atomically bank one shard (tmp + ``os.replace``). The on-disk
    schema matches the in-memory one key for key (``status_counts``
    rides as a JSON string) — a consumer written against
    ``generate_dataset``'s return works unchanged on a loaded
    shard."""
    import json as _json

    payload = {
        "v": np.asarray(shard["v"]),
        "kind": np.asarray(shard["kind"]),
        "sig": np.asarray(shard["sig"]),
        "mech_sig": np.asarray(shard["mech_sig"]),
        "x": np.asarray(shard["x"]),
        "y": np.asarray(shard["y"]),
        "valid": np.asarray(shard["valid"]),
        "lo": np.asarray(shard["lo"]),
        "hi": np.asarray(shard["hi"]),
        "t_end": np.asarray(shard["t_end"]),
        "option": np.asarray(int(shard.get("option", -1))),
        "status_counts": np.asarray(
            _json.dumps(shard.get("status_counts", {}))),
    }
    telemetry.atomic_savez(path, **payload)


def load_shard(path: str) -> Dict:
    """Load one shard; raises on a torn/old file (a training input is
    never an optional optimization)."""
    import json as _json

    with np.load(path, allow_pickle=False) as f:
        if int(f["v"]) != SHARD_VERSION:
            raise DatasetSignatureError(
                f"shard {path} has layout version {int(f['v'])}, "
                f"expected {SHARD_VERSION}")
        return {"v": int(f["v"]), "kind": str(f["kind"]),
                "sig": str(f["sig"]), "mech_sig": str(f["mech_sig"]),
                "x": np.asarray(f["x"]), "y": np.asarray(f["y"]),
                "valid": np.asarray(f["valid"]),
                "lo": np.asarray(f["lo"]), "hi": np.asarray(f["hi"]),
                "t_end": float(f["t_end"]),
                "option": int(f["option"]),
                "status_counts": _json.loads(str(f["status_counts"]))}


def load_shards(paths: Sequence[str], *,
                expect_sig: Optional[str] = None,
                expect_mech_sig: Optional[str] = None) -> Dict:
    """Concatenate shards into one training set.

    Every shard must agree on ``kind`` and ``mech_sig`` (and match
    ``expect_mech_sig``/``expect_sig`` when given) — the signature
    check that stops a stale shard from training against a different
    mechanism. Shards from DIFFERENT boxes/seeds of the same mechanism
    concatenate fine (that is the flywheel); their feature boxes merge
    to the union."""
    if not paths:
        raise ValueError("need at least one shard path")
    shards = [load_shard(p) for p in paths]
    first = shards[0]
    for p, s in zip(paths, shards):
        if s["kind"] != first["kind"]:
            raise DatasetSignatureError(
                f"shard {p} labels kind {s['kind']!r}, expected "
                f"{first['kind']!r}")
        if s["mech_sig"] != first["mech_sig"]:
            raise DatasetSignatureError(
                f"shard {p} was generated against a different "
                "mechanism (mech_sig mismatch)")
        if expect_mech_sig is not None \
                and s["mech_sig"] != expect_mech_sig:
            raise DatasetSignatureError(
                f"shard {p} does not match the current mechanism "
                "(mech_sig mismatch) — regenerate the dataset")
        if expect_sig is not None and s["sig"] != expect_sig:
            raise DatasetSignatureError(
                f"shard {p} has problem signature {s['sig'][:12]}…, "
                f"expected {expect_sig[:12]}… — different box/seed/"
                "solver configuration")
        if s.get("option", -1) != first.get("option", -1):
            raise DatasetSignatureError(
                f"shard {p} was labeled with equilibrium option "
                f"{s.get('option')}, the first shard with "
                f"{first.get('option')} — one model serves one "
                "constraint pair")
    return {
        "kind": first["kind"],
        "sig": first["sig"],
        "mech_sig": first["mech_sig"],
        "x": np.concatenate([s["x"] for s in shards]),
        "y": np.concatenate([s["y"] for s in shards]),
        "valid": np.concatenate([s["valid"] for s in shards]),
        "lo": np.min(np.stack([s["lo"] for s in shards]), axis=0),
        "hi": np.max(np.stack([s["hi"] for s in shards]), axis=0),
        "t_end": first["t_end"],
        "option": first.get("option", -1),
        "n_shards": len(shards),
    }
