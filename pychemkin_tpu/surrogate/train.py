"""Surrogate training: handwritten Adam over the plain-pytree MLP.

No optimizer library — Adam is ~15 lines over ``jax.tree_util`` and
the container bakes in jax only. The whole optimization (minibatch
draw, value-and-grad, moment updates) is one ``lax.scan`` under
``jit``, so even CI's tiny nets (2×32 hidden, ≤200 steps) train in
well under a second after the one-time trace.

Ensembles are M independent fits from different init/minibatch keys
over the SAME data — the disagreement between members is the
trust-interval signal :mod:`.verify` gates ignition predictions on
(an out-of-distribution input pulls the members apart; in-distribution
they collapse onto the data).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .dataset import DatasetSignatureError
from .model import Normalization, SurrogateModel, init_mlp, mlp_apply


def _adam_update(params, grads, m, v, step, *, lr, b1=0.9, b2=0.999,
                 eps=1e-8):
    m = jax.tree_util.tree_map(
        lambda mi, g: b1 * mi + (1.0 - b1) * g, m, grads)
    v = jax.tree_util.tree_map(
        lambda vi, g: b2 * vi + (1.0 - b2) * g * g, v, grads)
    # bias-corrected step size folds both corrections into one scalar
    scale = lr * jnp.sqrt(1.0 - b2 ** step) / (1.0 - b1 ** step)
    params = jax.tree_util.tree_map(
        lambda p, mi, vi: p - scale * mi / (jnp.sqrt(vi) + eps),
        params, m, v)
    return params, m, v


def train_member(key, Xn, Yn, sizes: Sequence[int], *,
                 steps: int = 400, lr: float = 1e-2,
                 batch_size: Optional[int] = None,
                 l2: float = 1e-6) -> Tuple[Any, np.ndarray]:
    """Fit one MLP on NORMALIZED (Xn, Yn); returns ``(params,
    per-step losses)``. Deterministic in ``key`` (init + minibatch
    schedule both derive from it)."""
    N = int(Xn.shape[0])
    if N == 0:
        raise ValueError("cannot train on an empty dataset")
    bs = min(int(batch_size or 64), N)
    key, init_key = jax.random.split(jnp.asarray(key))
    params = init_mlp(init_key, sizes)
    Xn = jnp.asarray(Xn, jnp.float64)
    Yn = jnp.asarray(Yn, jnp.float64)

    def loss_fn(p, xb, yb):
        err = mlp_apply(p, xb) - yb
        reg = sum(jnp.sum(W * W) for W, _ in p)
        return jnp.mean(err * err) + l2 * reg

    def step_fn(carry, step_key):
        p, m, v, t = carry
        idx = jax.random.randint(step_key, (bs,), 0, N)
        loss, grads = jax.value_and_grad(loss_fn)(p, Xn[idx], Yn[idx])
        p, m, v = _adam_update(p, grads, m, v, t + 1, lr=lr)
        return (p, m, v, t + 1), loss

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (params, _, _, _), losses = jax.lax.scan(
        step_fn, (params, zeros, zeros, jnp.array(0.0)),
        jax.random.split(key, int(steps)))
    return params, np.asarray(losses)


def fit_surrogate(data: Dict, *, hidden: Sequence[int] = (32, 32),
                  steps: int = 400, lr: float = 1e-2,
                  n_members: int = 3, seed: int = 0,
                  batch_size: Optional[int] = None,
                  l2: float = 1e-6
                  ) -> Tuple[SurrogateModel, List[np.ndarray]]:
    """Fit an ensemble on a dataset/shard dict (``x``/``y``/``valid``/
    ``lo``/``hi``/``sig``/``mech_sig``/``kind`` — the
    :mod:`.dataset` schema); returns ``(model, loss curves)``.

    Only ``valid`` rows (solver status OK) are fitted. Normalization
    stats and the trained-domain box ride inside the returned
    :class:`~pychemkin_tpu.surrogate.model.SurrogateModel` — the model
    file is self-contained for serving."""
    valid = np.asarray(data["valid"], bool)
    X = np.asarray(data["x"], np.float64)[valid]
    Y = np.asarray(data["y"], np.float64)[valid]
    if X.shape[0] < 2:
        raise DatasetSignatureError(
            f"dataset has {X.shape[0]} valid labeled rows — not enough "
            "to fit (check the box against the solver's ignition "
            "horizon / convergence)")
    # std floored: a constant feature (fixed-composition box) must
    # normalize to zero, not divide by zero
    x_mean, x_std = X.mean(0), np.maximum(X.std(0), 1e-8)
    y_mean, y_std = Y.mean(0), np.maximum(Y.std(0), 1e-8)
    Xn = (X - x_mean) / x_std
    Yn = (Y - y_mean) / y_std
    sizes = [X.shape[1]] + [int(h) for h in hidden] + [Y.shape[1]]

    members, curves = [], []
    for m in range(int(n_members)):
        params, losses = train_member(
            jax.random.PRNGKey(seed * 1000 + m), Xn, Yn, sizes,
            steps=steps, lr=lr, batch_size=batch_size, l2=l2)
        members.append(params)
        curves.append(losses)
    meta = {"t_end": data.get("t_end"), "n_train": int(X.shape[0]),
            "hidden": ",".join(str(int(h)) for h in hidden),
            "steps": int(steps), "seed": int(seed)}
    if data.get("option", -1) >= 0:
        # equilibrium: the constraint pair the labels were solved
        # under — the serve engine refuses any other option
        meta["option"] = int(data["option"])
    model = SurrogateModel(
        kind=data["kind"], members=tuple(members),
        norm=Normalization(
            x_mean=jnp.asarray(x_mean), x_std=jnp.asarray(x_std),
            y_mean=jnp.asarray(y_mean), y_std=jnp.asarray(y_std)),
        lo=jnp.asarray(data["lo"]), hi=jnp.asarray(data["hi"]),
        sig=data["sig"], mech_sig=data["mech_sig"],
        meta=meta)
    return model, curves


def training_curve_artifact(model: SurrogateModel,
                            curves: List[np.ndarray], *,
                            wall_s: float,
                            max_points: int = 200) -> Dict:
    """The JSON-ready training-curve artifact the CLI banks via
    :func:`pychemkin_tpu.telemetry.atomic_write_json` — per-member
    loss curves (subsampled to ``max_points``), final losses, and the
    model's identity block."""
    def _sub(c):
        c = np.asarray(c, np.float64)
        if c.shape[0] > max_points:
            idx = np.linspace(0, c.shape[0] - 1, max_points).astype(int)
            c = c[idx]
        return [round(float(v), 8) for v in c]

    return {
        "tool": "train_surrogate",
        "kind": model.kind,
        "sig": model.sig,
        "mech_sig": model.mech_sig,
        "meta": model.meta,
        "n_members": len(model.members),
        "wall_s": round(float(wall_s), 3),
        "final_losses": [round(float(np.asarray(c)[-1]), 8)
                         for c in curves],
        "curves": [_sub(c) for c in curves],
    }
