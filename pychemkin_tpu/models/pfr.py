"""Plug-flow reactor models.

TPU-native re-implementation of the reference's PFR family
(reference: src/ansys/chemkin/flowreactors/PFR.py): ``PlugFlowReactor``
(subclasses the batch-reactor base, as the reference does — PFR.py:46)
plus the ``PlugFlowReactor_EnergyConservation`` (:730) and
``PlugFlowReactor_FixedTemperature`` (:983) variants. The constructor
takes a :class:`Stream` inlet and pulls its flow rate and flow area
(reference: PFR.py:98-135); the momentum equation is ON by default
(reference: PFR.py:147). ``run()`` assembles one jitted
:func:`pychemkin_tpu.ops.pfr.solve_pfr` marching integration; the
ignition "delay" is reported as a distance in cm
(reference: batchreactor.py:623-640).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inlet import Stream, create_stream_from_mixture
from ..logger import logger
from ..mixture import Mixture
from ..ops import pfr as pfr_ops
from ..ops import reactors as reactor_ops
from ..resilience.status import name_of as status_name_of
from .batch import BatchReactors
from .reactormodel import STATUS_FAILED, STATUS_SUCCESS


class PlugFlowReactor(BatchReactors):
    """Base plug-flow reactor (reference: PFR.py:46)."""

    energy_type = "ENRG"
    problem_type = "PFR"

    def __init__(self, inlet: Stream, label: str = "PFR"):
        if not isinstance(inlet, Stream):
            raise TypeError("PFR requires a Stream inlet "
                            "(reference: PFR.py:51)")
        super().__init__(inlet, label)
        self._mdot = inlet.convert_to_mass_flowrate()
        self._flowarea = inlet.flowarea if inlet.flowarea > 0 else 1.0
        self._length = 0.0
        self._lengthset = False
        self._x_start = 0.0
        self._momentum = True       # ON by default (reference: PFR.py:147)
        self._pfr_solution = None

    # --- geometry (reference: PFR.py:151-337) ------------------------------
    @property
    def length(self) -> float:
        """Reactor length XEND [cm] (reference: PFR.py:151)."""
        return self._length

    @length.setter
    def length(self, length: float = 0.0):
        if length <= 0.0:
            raise ValueError("length must be positive")
        self._length = float(length)
        self._lengthset = True
        self._record_keyword("XEND", float(length))

    def set_start_position(self, x0: float):
        """XSTR (reference: PFR.py:182)."""
        self._x_start = float(x0)
        self.setkeyword("XSTR", float(x0))

    @property
    def diameter(self) -> float:
        """Duct diameter [cm] (reference: PFR.py:205)."""
        return 2.0 * np.sqrt(self._flowarea / np.pi)

    @diameter.setter
    def diameter(self, diam: float):
        if diam <= 0.0:
            raise ValueError("diameter must be positive")
        self._flowarea = np.pi * (diam / 2.0) ** 2
        self.setkeyword("DIAM", float(diam))

    def set_diameter_profile(self, x, diameter):
        """DPRO (reference: PFR.py:241) — stored as the equivalent area
        profile."""
        d = np.asarray(diameter, dtype=np.double)
        self.setprofile("AREA", x, np.pi * (d / 2.0) ** 2)

    @property
    def flowarea(self) -> float:
        """Flow area [cm^2] (reference: PFR.py:270)."""
        return self._flowarea

    @flowarea.setter
    def flowarea(self, area: float):
        if area <= 0.0:
            raise ValueError("flow area must be positive")
        self._flowarea = float(area)
        self.setkeyword("AREA", float(area))

    def set_flowarea_profile(self, x, area):
        """(reference: PFR.py:308)."""
        self.setprofile("AREA", x, area)

    @property
    def momentum_equation(self) -> bool:
        """Momentum equation toggle, ON by default
        (reference: PFR.py:147)."""
        return self._momentum

    @momentum_equation.setter
    def momentum_equation(self, on: bool):
        self._momentum = bool(on)
        self.setkeyword("MOMEN", bool(on))

    def set_volume_profile(self, time, volume):
        """Batch-only profile — meaningless for a PFR; fail loudly instead
        of being silently ignored by the PFR solve."""
        raise NotImplementedError("a PFR has no volume profile; use the "
                                  "area/diameter profiles")

    def set_pressure_profile(self, time, pressure):
        """Batch-only profile — PFR pressure follows the momentum
        equation (or stays at the inlet value with momentum off)."""
        raise NotImplementedError("a PFR has no pressure profile; pressure "
                                  "comes from the momentum equation")

    def set_inlet_viscosity(self, visc: float):
        """Accepted for deck parity (reference: PFR.py:338); the
        frictionless momentum equation does not use it."""
        self.setkeyword("VISC", float(visc))

    def set_pseudo_surface_velocity(self, vel: float):
        """Surface-chemistry option (reference: PFR.py:373); surface
        mechanisms are unsupported — recorded only."""
        self.setkeyword("PSV", float(vel))

    # --- inlet passthroughs (reference: PFR.py:392-439) --------------------
    @property
    def mass_flowrate(self) -> float:
        return self._mdot

    @property
    def inlet_velocity(self) -> float:
        rho = self._condition.RHO
        return self._mdot / (rho * self._flowarea)

    @property
    def vol_flowrate(self) -> float:
        return self._mdot / self._condition.RHO

    # --- solve -------------------------------------------------------------
    def validate_inputs(self) -> int:
        if not self._lengthset:
            logger.error("reactor length is required (XEND)")
            return 1
        if self._mdot <= 0.0:
            logger.error("inlet stream must carry a positive flow rate")
            return 2
        return 0

    def run(self) -> int:
        """March the plug-flow equations over the length
        (reference: PFR.py:627)."""
        self.consume_protected_keywords()
        if self.validate_inputs() != 0:
            self.runstatus = STATUS_FAILED
            return self.runstatus
        self._numbsolutionpoints = 0
        self._solution_rawarray = {}
        self._solution_mixturearray = []
        cond = self._condition
        n_out = 101
        if self._save_dt is not None:
            n_out = max(int(round(self._length / self._save_dt)) + 1, 2)
        t0 = time.perf_counter()
        sol = pfr_ops.solve_pfr(
            self._effective_mech(), self.energy_type,
            mdot=self._mdot, T0=cond.temperature, P0=cond.pressure,
            Y0=cond.Y, length=self._length, area=self._flowarea,
            x_start=self._x_start, n_out=n_out, rtol=self._rtol,
            atol=self._atol, momentum=self._momentum,
            area_profile=self._profile_or_none("AREA"),
            t_profile=self._profile_or_none("TPRO"),
            qloss_profile=self._profile_or_none("QPRO"),
            htc=self._htc, tamb=self._tamb,
            max_steps_per_segment=self._max_steps)
        self._pfr_solution = jax.device_get(sol)
        # ignition "delay" is the distance in cm (reference:
        # batchreactor.py:623-640); stored unscaled in the ms slot
        self._ignition_delay_ms = float(sol.ignition_distance)
        ok = bool(sol.success)
        status = int(self._pfr_solution.status)
        self.runstatus = STATUS_SUCCESS if ok else STATUS_FAILED
        self._record_solve(
            wall_s=round(time.perf_counter() - t0, 6), success=ok,
            status=status, status_name=status_name_of(status),
            n_steps=int(self._pfr_solution.n_steps),
            length=self._length, energy=self.energy_type)
        if not ok:
            logger.error("PFR integration failed (%s)",
                         status_name_of(status))
        return self.runstatus

    def get_ignition_delay(self) -> float:
        """Ignition DISTANCE in cm for a PFR (reference:
        batchreactor.py:623-640 reports distance, not time)."""
        if self._pfr_solution is None:
            logger.warning("reactor has not been run")
            return np.nan
        return float(self._pfr_solution.ignition_distance)

    def process_solution(self):
        """Axial profiles into the raw-array store (keys: distance,
        temperature, pressure, velocity, plus species)."""
        if self._pfr_solution is None:
            raise RuntimeError("run() the reactor first")
        sol = self._pfr_solution
        self._numbsolutionpoints = len(sol.x)
        raw = {
            "distance": np.asarray(sol.x),
            "time": np.asarray(sol.residence_time),
            "temperature": np.asarray(sol.T),
            "pressure": np.asarray(sol.P),
            "velocity": np.asarray(sol.u),
            "volume": np.asarray(1.0 / sol.rho),   # specific volume
        }
        Y = np.asarray(sol.Y)
        for k, name in enumerate(self._specieslist):
            raw[name] = Y[:, k]
        self._solution_rawarray = raw
        self._solution_Y = Y
        if self._TextOut or self._XMLOut:
            self.write_solution_files()
        return 0

    def set_inlet_stream(self, stream: Stream):
        """Replace the feed stream (state + mass flow) — used by the
        reactor network when synthesizing the internal inlet
        (reference network usage: hybridreactornetwork.py:1148)."""
        import copy as _copy
        if not isinstance(stream, Stream):
            raise TypeError("inlet must be a Stream")
        self._condition = _copy.deepcopy(stream)
        self._mdot = stream.convert_to_mass_flowrate()

    def get_exit_stream(self) -> "Stream":
        """Exit state as a Stream carrying the (constant) mass flow rate
        — what the reactor hands to a downstream network node
        (reference network usage: hybridreactornetwork.py:1061)."""
        if self._pfr_solution is None:
            raise RuntimeError("run() the reactor first")
        sol = self._pfr_solution
        mix = Mixture(self.chemistry)
        mix.temperature = float(np.asarray(sol.T)[-1])
        mix.pressure = float(np.asarray(sol.P)[-1])
        mix.Y = np.clip(np.asarray(sol.Y)[-1], 0.0, None)
        out = create_stream_from_mixture(mix, label=f"{self.label}.exit")
        out.mass_flowrate = self._mdot * 1.0
        out.flowarea = self._flowarea
        return out

    def run_sweep(self, T0s=None, P0s=None, Y0s=None, lengths=None, *,
                  min_slope=1.0):
        """Batched PFR sweep over inlet conditions (vmap over
        :func:`pychemkin_tpu.ops.pfr.solve_pfr`).

        Overrides the batch-reactor sweep, whose solver table has no PFR
        entry — inheriting it would crash with a bare KeyError. Any
        argument left None takes this reactor's configured value.
        Returns (ignition_distances_cm [B], success [B], status [B]) —
        the same three-array contract as the batch sweep, with
        ``status`` the per-element SolveStatus code."""
        if self.validate_inputs() != 0:
            raise ValueError("PFR is not fully configured (length, inlet)")
        cond = self._condition
        if T0s is None:
            T0s = np.asarray([cond.temperature])
        if P0s is None:
            P0s = cond.pressure
        if Y0s is None:
            Y0s = cond.Y
        if lengths is None:
            lengths = self._length

        sizes = [np.asarray(a).shape[0] for a in (T0s, P0s, lengths)
                 if np.asarray(a).ndim > 0]
        if np.asarray(Y0s).ndim > 1:
            sizes.append(np.asarray(Y0s).shape[0])
        B = max(sizes) if sizes else 1
        T0s = jnp.broadcast_to(jnp.asarray(T0s, jnp.float64), (B,))
        P0s = jnp.broadcast_to(jnp.asarray(P0s, jnp.float64), (B,))
        KK = np.asarray(Y0s).shape[-1]
        Y0s = jnp.broadcast_to(jnp.asarray(Y0s, jnp.float64), (B, KK))
        lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.float64), (B,))

        mech = self._effective_mech()

        def one(T0, P0, Y0, length):
            sol = pfr_ops.solve_pfr(
                mech, self.energy_type, mdot=self._mdot, T0=T0, P0=P0,
                Y0=Y0, length=length, area=self._flowarea,
                x_start=self._x_start, n_out=2, rtol=self._rtol,
                atol=self._atol, momentum=self._momentum,
                area_profile=self._profile_or_none("AREA"),
                t_profile=self._profile_or_none("TPRO"),
                qloss_profile=self._profile_or_none("QPRO"),
                htc=self._htc, tamb=self._tamb,
                max_steps_per_segment=self._max_steps,
                min_slope=min_slope)
            return sol.ignition_distance, sol.success, sol.status

        dists, ok, status = jax.vmap(one)(T0s, P0s, Y0s, lengths)
        return np.asarray(dists), np.asarray(ok), np.asarray(status)

    @property
    def exit_stream(self) -> Stream:
        """Outlet stream at the last grid point (alias of
        :meth:`get_exit_stream`)."""
        return self.get_exit_stream()


class PlugFlowReactor_EnergyConservation(PlugFlowReactor):
    """PFR with the energy equation (reference: PFR.py:730). Inherits the
    wall-heat-transfer property surface of the ENRG batch family
    (heat_loss_rate / heat_transfer_coefficient / ambient_temperature —
    reference: PFR.py:797-960)."""

    energy_type = "ENRG"

    # heat-transfer surface identical to the batch ENRG variants
    @property
    def heat_loss_rate(self) -> float:
        """QLOS per unit length [erg/(cm s)]."""
        return self._qloss

    @heat_loss_rate.setter
    def heat_loss_rate(self, value: float):
        self._qloss = float(value)
        self._record_keyword("QLOS", float(value))
        self.setprofile("QPRO", [0.0, 1e12], [value, value])

    @property
    def heat_transfer_coefficient(self) -> float:
        return self._htc

    @heat_transfer_coefficient.setter
    def heat_transfer_coefficient(self, value: float = 0.0):
        if value < 0.0:
            raise ValueError("heat transfer coefficient must be >= 0")
        self._htc = float(value)
        self._record_keyword("HTC", float(value))

    @property
    def ambient_temperature(self) -> float:
        return self._tamb

    @ambient_temperature.setter
    def ambient_temperature(self, value: float = 0.0):
        if value <= 0.0:
            raise ValueError("ambient temperature must be positive")
        self._tamb = float(value)
        self._record_keyword("TAMB", float(value))

    def set_velocity_profile(self, x, velocity):
        """Accepted for deck parity (reference: PFR.py:961); velocity
        follows from continuity+momentum here."""
        self.setprofile("VPROX", x, velocity)


class PlugFlowReactor_FixedTemperature(PlugFlowReactor):
    """PFR with prescribed T(x) (reference: PFR.py:983)."""

    energy_type = "TGIV"

    def set_temperature_profile(self, x, temperature):
        """T(x) profile over distance (reference: PFR.py:1048)."""
        self.setprofile("TPRO", x, temperature)
