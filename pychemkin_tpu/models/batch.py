"""0-D batch (closed homogeneous) reactor models.

TPU-native re-implementation of the reference's batch-reactor family
(reference: src/ansys/chemkin/batchreactors/batchreactor.py): the
``BatchReactors`` base plus the four concrete problem types

- ``GivenPressureBatchReactor_FixedTemperature``   (CONP + TGIV, :1649)
- ``GivenPressureBatchReactor_EnergyConservation`` (CONP + ENRG, :1775)
- ``GivenVolumeBatchReactor_FixedTemperature``     (CONV + TGIV, :2070)
- ``GivenVolumeBatchReactor_EnergyConservation``   (CONV + ENRG, :2196)

Where the reference's ``run()`` marshals keywords into the native library
and blocks in ``KINAll0D_Calculate`` (batchreactor.py:1161, 1149-1158),
here ``run()`` assembles a pure solve with
:func:`pychemkin_tpu.ops.reactors.solve_batch` — jitted, and reusable
under ``vmap``/``shard_map`` for parameter sweeps via
:meth:`BatchReactors.run_sweep`.

Units CGS; ignition delay is returned in MILLISECONDS, matching the
reference's sec -> msec conversion (batchreactor.py:613).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..logger import logger
from ..mixture import Mixture
from ..ops import reactors as reactor_ops
from ..ops import sensitivity as sens_ops
from ..resilience.status import name_of
from .reactormodel import (
    STATUS_FAILED,
    STATUS_NOT_RUN,
    STATUS_SUCCESS,
    ReactorModel,
)

#: default solver tolerances (reference: batchreactor.py:91-92)
DEFAULT_ATOL = 1.0e-12
DEFAULT_RTOL = 1.0e-6


class BatchReactors(ReactorModel):
    """Base 0-D transient closed-reactor model
    (reference: batchreactor.py:52)."""

    #: problem/energy types, set by subclasses
    problem_type = "CONP"
    energy_type = "ENRG"

    def __init__(self, reactor_condition: Mixture, label: str):
        super().__init__(reactor_condition, label)
        self._atol = DEFAULT_ATOL
        self._rtol = DEFAULT_RTOL
        self._time = 0.0
        self._timeset = False
        self._volume = reactor_condition.volume
        self._area = 0.0
        self._qloss = 0.0
        self._htc = 0.0
        self._tamb = 298.15
        self._htarea = 0.0
        self._force_nonneg = False
        self._save_dt: Optional[float] = None
        self._ignition_mode = reactor_ops.IGN_T_INFLECTION
        self._ignition_kwargs: Dict = {}
        self._stop_after_ignition = False
        self._ignition_delay_ms = np.nan
        self._solution = None
        self._max_steps = 100_000

    # --- geometry (reference: batchreactor.py:110-176) ---------------------
    @property
    def volume(self) -> float:
        """Reactor volume [cm^3] (reference: batchreactor.py:110)."""
        return self._volume

    @volume.setter
    def volume(self, value: float):
        if value <= 0.0:
            raise ValueError("volume must be positive")
        self._volume = float(value)

    @property
    def area(self) -> float:
        """Internal surface area [cm^2] (reference:
        batchreactor.py:142)."""
        return self._area

    @area.setter
    def area(self, value: float = 0.0):
        if value < 0.0:
            raise ValueError("area must be non-negative")
        self._area = float(value)

    # --- solver controls (reference: batchreactor.py:177-372) --------------
    @property
    def tolerances(self) -> Tuple[float, float]:
        """(atol, rtol), defaults (1e-12, 1e-6)
        (reference: batchreactor.py:177-215)."""
        return self._atol, self._rtol

    @tolerances.setter
    def tolerances(self, tolerances: Tuple[float, float]):
        atol, rtol = tolerances
        if atol <= 0.0 or rtol <= 0.0:
            raise ValueError("tolerances must be positive")
        self._atol = float(atol)
        self._rtol = float(rtol)
        self.setkeyword("ATOL", float(atol))
        self.setkeyword("RTOL", float(rtol))

    @property
    def force_nonnegative(self) -> bool:
        """(reference: batchreactor.py:216; the SDIRK integrator keeps
        fractions near-nonnegative by construction — the flag is accepted
        and recorded)."""
        return self._force_nonneg

    @force_nonnegative.setter
    def force_nonnegative(self, mode: bool = False):
        self._force_nonneg = bool(mode)
        self.setkeyword("NNEG", bool(mode))

    def set_solver_initial_timestep_size(self, size: float):
        """(reference: batchreactor.py:247)."""
        self.setkeyword("ISTP", float(size))

    def set_solver_max_timestep_size(self, size: float):
        """(reference: batchreactor.py:263)."""
        self.setkeyword("MAXDT", float(size))

    @property
    def timestep_for_saving_solution(self) -> Optional[float]:
        """Output-grid spacing [s] (reference: batchreactor.py:279);
        defaults to end_time/100 when unset."""
        return self._save_dt

    @timestep_for_saving_solution.setter
    def timestep_for_saving_solution(self, delta_time: float):
        if delta_time <= 0.0:
            raise ValueError("saving timestep must be positive")
        self._save_dt = float(delta_time)
        self.setkeyword("DELT", float(delta_time))

    @property
    def timestep_for_printing_solution(self) -> Optional[float]:
        return self.getkeyword("DTSV")

    @timestep_for_printing_solution.setter
    def timestep_for_printing_solution(self, delta_time: float):
        self.setkeyword("DTSV", float(delta_time))

    def adaptive_solution_saving(self, mode: bool = True,
                                 delta_temperature: float = 10.0,
                                 delta_species: float = 0.05):
        """The reference's event-driven save refinement
        (batchreactor.py:373, ADAP/DTMN/DXMN keywords). The TPU build
        integrates with in-step event accumulators instead of dense
        output, so ignition timing does not depend on the save grid; the
        keywords are recorded for deck parity."""
        self.setkeyword("ADAP", bool(mode))
        self.setkeyword("DTMN", float(delta_temperature))
        self.setkeyword("DXMN", float(delta_species))

    # --- ignition delay (reference: batchreactor.py:462-643) ---------------
    def set_ignition_delay(self, method: str = "T_inflection",
                           val: float = 0.0, target: str = ""):
        """Choose the ignition-delay definition (reference:
        batchreactor.py:462): 'T_inflection' (TIFP, max dT/dt),
        'T_rise' (DTIGN, rise of ``val`` K over the initial T),
        'T_ignition' (TLIM, absolute T of ``val`` K),
        'Species_peak' (KLIM, peak of species ``target``)."""
        if method == "T_inflection":
            self._ignition_mode = reactor_ops.IGN_T_INFLECTION
            self._ignition_kwargs = {}
            self.setkeyword("TIFP", True)
        elif method == "T_rise":
            if val <= 0.0:
                raise ValueError("temperature rise value must be > 0")
            self._ignition_mode = reactor_ops.IGN_T_RISE
            self._ignition_kwargs = {"delta_T": float(val)}
            self.setkeyword("DTIGN", float(val))
        elif method == "T_ignition":
            if val <= 0.0:
                raise ValueError("ignition temperature must be > 0")
            self._ignition_mode = reactor_ops.IGN_T_IGNITION
            self._ignition_kwargs = {"T_limit": float(val)}
            self.setkeyword("TLIM", float(val))
        elif method == "Species_peak":
            if target not in self._specieslist:
                raise ValueError(
                    "target species is assigned as a string, e.g. 'OH'")
            self._ignition_mode = reactor_ops.IGN_SPECIES_PEAK
            self._ignition_kwargs = {
                "species_index": self._specieslist.index(target)}
            self.setkeyword("KLIM", target)
        else:
            raise ValueError(f"ignition definition {method!r} is not "
                             "recognized")

    def stop_after_ignition(self):
        """(reference: batchreactor.py:538, ISTOP keyword). Recorded; the
        batched integrator always runs to end time so that one compiled
        program serves every sweep element."""
        self._stop_after_ignition = True
        self.setkeyword("ISTOP", True)

    def get_ignition_delay(self) -> float:
        """Ignition delay in MILLISECONDS (reference:
        batchreactor.py:545-643, sec->msec at :613); nan if not detected."""
        if self.runstatus == STATUS_NOT_RUN:
            logger.warning("reactor has not been run")
            return np.nan
        if not np.isfinite(self._ignition_delay_ms):
            logger.warning("no ignition detected "
                           "(reference: batchreactor.py:583-609)")
        return self._ignition_delay_ms

    # --- profiles (reference: batchreactor.py:644-733, 2005-2069) ----------
    def set_volume_profile(self, time, volume):
        """VPRO (reference: batchreactor.py:644)."""
        self.setprofile("VPRO", time, volume)

    def set_pressure_profile(self, time, pressure):
        """PPRO (reference: batchreactor.py:679)."""
        self.setprofile("PPRO", time, pressure)

    def set_surfacearea_profile(self, time, area):
        """AINT — internal surface area for surface chemistry (reference:
        batchreactor.py:714). Recorded for deck parity only: surface
        mechanisms are unsupported in this build, so the profile has no
        effect on the gas-phase solve."""
        self.setprofile("AINT", time, area)

    def set_temperature_profile(self, time, temperature):
        """TPRO (reference: batchreactor.py:1753). Only honored by the
        fixed-temperature (TGIV) variants."""
        self.setprofile("TPRO", time, temperature)

    def set_heat_loss_profile(self, time, qloss):
        """QPRO (reference: batchreactor.py:2037)."""
        self.setprofile("QPRO", time, qloss)

    def set_heat_transfer_area_profile(self, time, area):
        """Heat-transfer-area A(t) profile, honored by the Q = HTC * A(t) *
        (Tamb - T) wall term (reference: batchreactor.py:2005)."""
        self.setprofile("AREA", time, area)

    # --- end time ----------------------------------------------------------
    @property
    def time(self) -> float:
        """Simulation end time [s] (reference: batchreactor.py:1722)."""
        return self._time

    @time.setter
    def time(self, value: float = 0.0):
        if value <= 0.0:
            raise ValueError("end time must be positive")
        self._time = float(value)
        self._timeset = True
        self._record_keyword("TIME", float(value))

    def validate_inputs(self) -> int:
        """(reference: batchreactor.py:794): end time is required."""
        if not self._timeset:
            logger.error("simulation end time is required (TIME)")
            return 1
        return 0

    # --- solve assembly ----------------------------------------------------
    def _profile_or_none(self, key: str):
        prof = self.getprofile(key)
        if prof is None:
            return None
        # device arrays: the profile is indexed with traced values inside
        # the jitted integrator
        return reactor_ops.Profile(x=jnp.asarray(prof.pos),
                                   y=jnp.asarray(prof.value))

    def _build_solve_kwargs(self, n_out: int) -> Dict:
        mech = self._effective_mech()
        constraint = None
        if self.problem_type == "CONP":
            constraint = self._profile_or_none("PPRO")
        else:
            constraint = self._profile_or_none("VPRO")
        tprof = self._profile_or_none("TPRO")
        qprof = self._profile_or_none("QPRO")
        if qprof is None and self._qloss != 0.0:
            qprof = reactor_ops.constant_profile(self._qloss)
        return dict(
            mech=mech,
            problem=self.problem_type,
            energy=self.energy_type,
            n_out=n_out,
            rtol=self._rtol,
            atol=self._atol,
            constraint_profile=constraint,
            t_profile=tprof,
            qloss_profile=qprof,
            area_profile=self._profile_or_none("AREA"),
            volume=self._volume,
            htc=self._htc,
            tamb=self._tamb,
            area=self._htarea,
            ignition_mode=self._ignition_mode,
            ignition_kwargs=self._ignition_kwargs,
            max_steps_per_segment=self._max_steps,
        )

    def run(self) -> int:
        """Integrate the reactor (reference: batchreactor.py:1161 runs the
        whole problem in one blocking native call; here one jitted
        solve)."""
        # full-keyword decks route TIME/TEMP/PRES/VOL/ATOL/RTOL here
        # (reference: batchreactor.py:822 __process_keywords_withFullInputs)
        self.consume_protected_keywords()
        if self.validate_inputs() != 0:
            self.runstatus = STATUS_FAILED
            return self.runstatus
        cond = self._condition
        # a re-run invalidates any previously processed solution
        self._numbsolutionpoints = 0
        self._solution_rawarray = {}
        self._solution_mixturearray = []
        n_out = 101
        if self._save_dt is not None:
            n_out = max(int(round(self._time / self._save_dt)) + 1, 2)
        kwargs = self._build_solve_kwargs(n_out)
        t0 = time.perf_counter()
        sol = reactor_ops.solve_batch(
            T0=cond.temperature, P0=cond.pressure, Y0=cond.Y,
            t_end=self._time, **kwargs)
        self._solution = jax.device_get(sol)
        wall_s = time.perf_counter() - t0
        ign_s = float(self._solution.ignition_time)
        self._ignition_delay_ms = ign_s * 1.0e3
        ok = bool(self._solution.success)
        status = int(self._solution.status)
        self.runstatus = STATUS_SUCCESS if ok else STATUS_FAILED
        self._record_solve(
            wall_s=round(wall_s, 6), success=ok,
            status=status, status_name=name_of(status),
            n_steps=int(self._solution.n_steps),
            n_rejected=int(self._solution.n_rejected),
            n_newton=int(self._solution.n_newton),
            ignition_delay_ms=(ign_s * 1e3 if np.isfinite(ign_s)
                               else None),
            t_end=self._time)
        if not ok:
            logger.error("batch-reactor integration failed (%s)",
                         name_of(status))
        return self.runstatus

    # --- sensitivity & ROP analysis (ASEN / AROP consumption) ----------

    def _require_asen(self):
        if not self._sensitivity:
            raise RuntimeError(
                "sensitivity analysis is not enabled; call "
                "setsensitivityanalysis() before run() "
                "(reference ASEN keyword, reactormodel.py:1522)")

    def get_ignition_sensitivity(self, *, eps=0.05):
        """Normalized ignition-delay sensitivities d ln(tau)/d ln(A_i)
        for every reaction, computed as ONE vmapped batch of perturbed
        integrations (the ASEN output of the ignition workflow). Returns
        :class:`pychemkin_tpu.ops.sensitivity.IgnitionSensitivity`."""
        self._require_asen()
        cond = self._condition
        return sens_ops.ignition_delay_sensitivity(
            self._effective_mech(), self.problem_type, self.energy_type,
            cond.temperature, cond.pressure, np.asarray(cond.Y),
            self._time, eps=eps)

    def get_sensitivity_profile(self, *, eps=0.05, n_out=51):
        """Normalized T/species profile sensitivities (ASEN profile
        output). Returns
        :class:`pychemkin_tpu.ops.sensitivity.ProfileSensitivity`."""
        self._require_asen()
        cond = self._condition
        return sens_ops.profile_sensitivity(
            self._effective_mech(), self.problem_type, self.energy_type,
            cond.temperature, cond.pressure, np.asarray(cond.Y),
            self._time, eps=eps, n_out=n_out)

    def get_ROP_table(self):
        """Rate-of-production table over the saved solution profiles
        (AROP output, reference reactormodel.py:1585). Requires a
        successful run(); returns
        :class:`pychemkin_tpu.ops.sensitivity.ROPTable`."""
        if not self._rop_analysis:
            raise RuntimeError(
                "ROP analysis is not enabled; call setROPanalysis() "
                "before run() (reference AROP keyword)")
        if self._solution is None or not self.checkrunstatus():
            raise RuntimeError("run() the reactor successfully first")
        sol = self._solution
        return sens_ops.rop_analysis(self._effective_mech(), sol.times,
                                     sol.T, sol.P, sol.Y)

    def get_dominant_reactions(self, species_name: str):
        """Reactions dominating production/destruction of a species,
        filtered by the EPSR threshold (reference reactormodel.py:1614).
        Returns (reaction indices, peak |contribution| values)."""
        table = self.get_ROP_table()
        mech = self._effective_mech()
        k = mech.species_index(species_name)
        return sens_ops.dominant_reactions(
            table, mech, k, threshold=self._rop_threshold)

    def run_sweep(self, T0s=None, P0s=None, Y0s=None, t_ends=None, *,
                  chunk_size=None, checkpoint_path=None,
                  job_report=None, driver_kwargs=None):
        """Batched ignition-delay sweep over initial conditions — the TPU
        replacement for the reference's serial Python loops (SURVEY.md
        §2.3; tests/integration_tests/ignitiondelay.py:127-144). Any
        argument left None takes this reactor's configured value; the
        reactor's profiles, heat-transfer settings, and tolerances apply
        to every sweep element exactly as in :meth:`run`.

        The sweep runs under the durable-job driver
        (:func:`pychemkin_tpu.resilience.driver.run_sweep_job`):
        ``chunk_size`` splits the batch into sequential same-shape
        jitted calls, ``checkpoint_path`` banks every completed chunk
        atomically so a killed process resumes instead of restarting
        (SIGTERM finishes the in-flight chunk, banks, and raises
        :class:`~pychemkin_tpu.resilience.driver.JobInterrupted` with
        the resumable rc), and ``job_report`` (a dict) is filled in
        place with the driver's
        :class:`~pychemkin_tpu.resilience.driver.SweepJobReport`.

        Returns (ignition_delays_ms [B], success [B], status [B]) —
        ``status`` carries each element's SolveStatus code."""
        from ..resilience import checkpoint as _checkpoint
        from ..resilience import driver as _driver

        cond = self._condition
        if T0s is None:
            T0s = np.asarray([cond.temperature])
        if P0s is None:
            P0s = cond.pressure
        if Y0s is None:
            Y0s = cond.Y
        if t_ends is None:
            if not self._timeset:
                raise ValueError("end time required (set .time)")
            t_ends = self._time

        sizes = [np.asarray(a).shape[0] for a in (T0s, P0s, t_ends)
                 if np.asarray(a).ndim > 0]
        if np.asarray(Y0s).ndim > 1:
            sizes.append(np.asarray(Y0s).shape[0])
        B = max(sizes) if sizes else 1
        T0s = jnp.broadcast_to(jnp.asarray(T0s, jnp.float64), (B,))
        P0s = jnp.broadcast_to(jnp.asarray(P0s, jnp.float64), (B,))
        KK = np.asarray(Y0s).shape[-1]
        Y0s = jnp.broadcast_to(jnp.asarray(Y0s, jnp.float64), (B, KK))
        t_ends = jnp.broadcast_to(jnp.asarray(t_ends, jnp.float64), (B,))

        kwargs = self._build_solve_kwargs(n_out=2)

        def one(T0, P0, Y0, t_end):
            sol = reactor_ops.solve_batch(T0=T0, P0=P0, Y0=Y0, t_end=t_end,
                                          **kwargs)
            return sol.ignition_time, sol.success, sol.status

        vm = jax.vmap(one)

        sig = None
        if checkpoint_path is not None:
            sig = _checkpoint.config_signature(
                "batch.run_sweep", type(self).__name__,
                cfg={k: v for k, v in kwargs.items() if k != "mech"},
                arrays=(T0s, P0s, Y0s, t_ends), tree=kwargs["mech"])

        def index_solve(idx):
            t, ok, st = vm(T0s[idx], P0s[idx], Y0s[idx], t_ends[idx])
            return {"times": t, "ok": ok, "status": st}

        results, _report = _driver.run_vmapped_sweep_job(
            index_solve, B, chunk_size=chunk_size,
            checkpoint_path=checkpoint_path, signature=sig,
            result_keys=("times", "ok", "status"),
            job_report=job_report, label="batch.run_sweep",
            **(driver_kwargs or {}))
        return (results["times"] * 1.0e3, results["ok"],
                results["status"])

    # --- solution retrieval (reference: batchreactor.py:1263-1648) ---------
    def get_solution_size(self) -> Tuple[int, int]:
        """(n_reactors, n_solution_points)
        (reference: batchreactor.py:1263)."""
        if self._solution is None:
            return 1, 0
        return 1, len(self._solution.times)

    def process_solution(self):
        """Unpack the solve result into the raw-array store
        (reference: batchreactor.py:1335 copies the arrays out of the
        native library; here they are already arrays)."""
        if self._solution is None:
            raise RuntimeError("run() the reactor first")
        sol = self._solution
        self._numbsolutionpoints = len(sol.times)
        raw = {
            "time": np.asarray(sol.times),
            "temperature": np.asarray(sol.T),
            "pressure": np.asarray(sol.P),
            "volume": np.asarray(sol.volume),
        }
        Y = np.asarray(sol.Y)
        for k, name in enumerate(self._specieslist):
            raw[name] = Y[:, k]
        self._solution_rawarray = raw
        self._solution_Y = Y
        self._solution_mixturearray = []
        if self._TextOut or self._XMLOut:
            self.write_solution_files()
        return 0

    def create_solution_mixtures(self) -> int:
        """Materialize a Mixture per solution point
        (reference: batchreactor.py:1487)."""
        if not self.getrawsolutionstatus():
            self.process_solution()
        self._solution_mixturearray = []
        raw = self._solution_rawarray
        for i in range(self._numbsolutionpoints):
            mix = Mixture(self.chemistry)
            mix.temperature = float(raw["temperature"][i])
            mix.pressure = float(raw["pressure"][i])
            mix.Y = self._solution_Y[i]
            mix.volume = float(raw["volume"][i])
            self._solution_mixturearray.append(mix)
        return 0

    def get_solution_mixture(self, time: float) -> Mixture:
        """Mixture at the solution point closest to ``time``
        (reference: batchreactor.py:1550)."""
        if not self._solution_mixturearray:
            self.create_solution_mixtures()
        idx = int(np.argmin(np.abs(self._solution_rawarray["time"] - time)))
        return self._solution_mixturearray[idx]

    def get_solution_mixture_at_index(self, solution_index: int) -> Mixture:
        """(reference: batchreactor.py:1599)."""
        if not self._solution_mixturearray:
            self.create_solution_mixtures()
        return self._solution_mixturearray[solution_index]


class GivenPressureBatchReactor_FixedTemperature(BatchReactors):
    """CONP + TGIV (reference: batchreactor.py:1649)."""

    problem_type = "CONP"
    energy_type = "TGIV"

    def __init__(self, reactor_condition: Mixture, label: str = "CONPT"):
        super().__init__(reactor_condition, label)


class GivenPressureBatchReactor_EnergyConservation(BatchReactors):
    """CONP + ENRG (reference: batchreactor.py:1775) — the north-star
    configuration of the rebuild (SURVEY.md §3.3)."""

    problem_type = "CONP"
    energy_type = "ENRG"

    def __init__(self, reactor_condition: Mixture, label: str = "CONP"):
        super().__init__(reactor_condition, label)

    # heat-transfer options (reference: batchreactor.py:1883-2004)
    @property
    def heat_loss_rate(self) -> float:
        """QLOS [erg/s] (positive = loss)."""
        return self._qloss

    @heat_loss_rate.setter
    def heat_loss_rate(self, value: float):
        self._qloss = float(value)
        self._record_keyword("QLOS", float(value))

    @property
    def heat_transfer_coefficient(self) -> float:
        """HTC [erg/(cm^2 K s)]."""
        return self._htc

    @heat_transfer_coefficient.setter
    def heat_transfer_coefficient(self, value: float = 0.0):
        if value < 0.0:
            raise ValueError("heat transfer coefficient must be >= 0")
        self._htc = float(value)
        self._record_keyword("HTC", float(value))

    @property
    def ambient_temperature(self) -> float:
        """TAMB [K]."""
        return self._tamb

    @ambient_temperature.setter
    def ambient_temperature(self, value: float = 0.0):
        if value <= 0.0:
            raise ValueError("ambient temperature must be positive")
        self._tamb = float(value)
        self._record_keyword("TAMB", float(value))

    @property
    def heat_transfer_area(self) -> float:
        """AREAQ [cm^2]."""
        return self._htarea

    @heat_transfer_area.setter
    def heat_transfer_area(self, value: float = 0.0):
        if value < 0.0:
            raise ValueError("heat transfer area must be >= 0")
        self._htarea = float(value)
        self._record_keyword("AREAQ", float(value))


class GivenVolumeBatchReactor_FixedTemperature(BatchReactors):
    """CONV + TGIV (reference: batchreactor.py:2070)."""

    problem_type = "CONV"
    energy_type = "TGIV"

    def __init__(self, reactor_condition: Mixture, label: str = "CONVT"):
        super().__init__(reactor_condition, label)


class GivenVolumeBatchReactor_EnergyConservation(
        GivenPressureBatchReactor_EnergyConservation):
    """CONV + ENRG (reference: batchreactor.py:2196). Inherits the
    heat-transfer surface of the ENRG family."""

    problem_type = "CONV"
    energy_type = "ENRG"

    def __init__(self, reactor_condition: Mixture, label: str = "CONV"):
        BatchReactors.__init__(self, reactor_condition, label)
