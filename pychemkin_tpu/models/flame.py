"""Generic steady-state 1-D flame model base (reference flame.py:37).

``Flame`` combines the reactor-model keyword machinery, the steady-state
solver controls, and the 1-D grid controls — exactly the reference's
``Flame(ReactorModel, SteadyStateSolver, Grid)`` mixin stack — and holds
the transport-model / differencing / boundary-type selections that the
flame solver core (:mod:`pychemkin_tpu.ops.flame1d`) consumes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..inlet import Stream
from ..logger import logger
from .grid import Grid
from .reactormodel import ReactorModel
from .steadystatesolver import SteadyStateSolver


class Flame(ReactorModel, SteadyStateSolver, Grid):
    """Generic steady-state, one-dimensional flame model
    (reference flame.py:37-117)."""

    def __init__(self, fuelstream: Stream, label: str):
        if not isinstance(fuelstream, Stream):
            raise TypeError("the first argument must be a Stream object.")
        ReactorModel.__init__(self, fuelstream, label)
        if not self.chemistry.verify_transport_data():
            # transport property data is required by the flame models
            # (reference flame.py:64-69)
            raise ValueError(
                "transport properties are required by flame models; "
                "load the mechanism with transport data")
        SteadyStateSolver.__init__(self)
        Grid.__init__(self)
        self.mass_flow_rate = fuelstream.mass_flowrate
        self.temp_profile_set = False
        self.grid_T_profile = False
        self.EnergyTypes = {"ENERGY": 1, "GivenT": 2}
        self._energytype = 1
        # transport mode: 0 not set, 1 mixture-averaged, 2 multicomponent,
        # 3 fixed Lewis number (reference flame.py:92 + :257-304)
        self.transport_mode = 0
        self._lewis = 1.0
        self._thermal_diffusion = False
        self._upwind = True                  # WDIF default (flame.py:134)
        self._species_flux_bc = True         # FLUX default
        self._numbsolutionpoints = 0
        self._temp_profile: Optional[tuple] = None

    # --- temperature profile (reference flame.py:100-130) -----------------

    def set_temperature_profile(self, x, temp) -> int:
        """Specify a temperature profile TPRO (reference flame.py:100).
        Required for the given-temperature flame models; for energy-
        equation models it seeds the initial temperature estimate
        (unless the automatic equilibrium estimate TPROF is on)."""
        x = np.asarray(x, dtype=np.float64)
        temp = np.asarray(temp, dtype=np.float64)
        if x.shape != temp.shape or x.ndim != 1 or x.size < 2:
            logger.error("temperature profile needs matching 1-D arrays")
            return 1
        if not np.all(np.diff(x) > 0):
            logger.error("profile positions must be strictly increasing")
            return 1
        self.setprofile("TPRO", x, temp)
        self._temp_profile = (x, temp)
        self.temp_profile_set = True
        return 0

    def temperature_profile_fn(self):
        """The TPRO data as a callable T(x) (clamped linear interp)."""
        if self._temp_profile is None:
            return None
        x, temp = self._temp_profile
        return lambda xi: float(np.interp(xi, x, temp))

    def use_temp_profile_initial_mesh(self, on: bool = False):
        """Use the TPRO grid points as the initial mesh
        (reference flame.py:122 USE_TPRO_GRID)."""
        self.grid_T_profile = bool(on)

    # --- differencing (reference flame.py:134-152) -------------------------

    # reference flame.py:122 spells the method with a typo; keep the
    # misspelled alias so reference scripts run unchanged
    use_temp_profiel_initial_mesh = None  # assigned after class body

    def set_mesh_keywords(self) -> int:
        """Mirror the Grid mixin's mesh parameters into the keyword
        table (reference flame.py:154); the typed solve reads the
        attributes directly."""
        for key, val in (("NPTS", self.numb_grid_points),
                         ("NTOT", self.max_numb_grid_points),
                         ("NADP", self.max_numb_adapt_points),
                         ("GRAD", self.gradient),
                         ("CURV", self.curvature),
                         ("XSTR", self.starting_x),
                         ("XEND", self.ending_x)):
            if val is not None:
                self._record_keyword(key, val)
        return 0

    def set_convection_differencing_type(self, mode: str):
        """'central' (CDIF) or 'upwind' (WDIF, default)."""
        mode = mode.lower()
        if mode.startswith("c"):
            self._upwind = False
            self.removekeyword("WDIF")
            self.setkeyword("CDIF", True)
        elif mode.startswith("u") or mode.startswith("w"):
            self._upwind = True
            self.removekeyword("CDIF")
            self.setkeyword("WDIF", True)
        else:
            logger.error("differencing mode must be 'central' or 'upwind'")

    # --- transport models (reference flame.py:257-318) ---------------------

    _TRANSPORT_KEYS = ("MIX", "MULT", "LEWIS")

    def _set_transport_keyword(self, key, value=True):
        for k in self._TRANSPORT_KEYS:
            if k != key:
                self.removekeyword(k)
        self.setkeyword(key, value)

    def use_mixture_averaged_transport(self):
        """MIX (reference flame.py:257)."""
        self.transport_mode = 1
        self._set_transport_keyword("MIX")

    def use_multicomponent_transport(self):
        """MULT (reference flame.py:267): ordinary diffusion from a
        Stefan-Maxwell solve at every grid face
        (:func:`pychemkin_tpu.ops.transport.stefan_maxwell_fluxes`)."""
        self.transport_mode = 2
        self._set_transport_keyword("MULT")

    def use_fixed_Lewis_number_transport(self, Lewis: float = 1.0):
        """LEWIS (reference flame.py:279)."""
        if Lewis <= 0:
            logger.error("Lewis number must be positive")
            return
        self.transport_mode = 3
        self._lewis = float(Lewis)
        self._set_transport_keyword("LEWIS", float(Lewis))

    def use_thermal_diffusion(self, mode: bool = True):
        """TDIF — include the Soret term (reference flame.py:305)."""
        self._thermal_diffusion = bool(mode)
        self.setkeyword("TDIF", bool(mode))

    # --- species boundary types (reference flame.py:319-344) ---------------

    def set_species_boundary_types(self, mode: str = "comp"):
        """'comp' (fixed inlet composition) or 'flux' (flux balance,
        default in this build — reference flame.py:319)."""
        mode = mode.lower()
        if mode.startswith("c"):
            self._species_flux_bc = False
            self.removekeyword("FLUX")
            self.setkeyword("COMP", True)
        elif mode.startswith("f"):
            self._species_flux_bc = True
            self.removekeyword("COMP")
            self.setkeyword("FLUX", True)
        else:
            logger.error("species boundary mode must be 'comp' or 'flux'")

    # --- solver-core option assembly ---------------------------------------

    def _transport_model_name(self) -> str:
        return {2: "MULT", 3: "LEWIS"}.get(self.transport_mode, "MIX")

    def _flame_solver_options(self) -> dict:
        """Options dict for ops.flame1d.solve_flame shared by every
        concrete flame model."""
        return dict(
            upwind=self._upwind,
            transport_model=self._transport_model_name(),
            lewis=self._lewis,
            soret=self._thermal_diffusion,
            species_flux_bc=self._species_flux_bc,
            ss_atol=float(self.SSabsolute_tolerance),
            ss_rtol=float(self.SSrelative_tolerance),
            ts_dt=float(self.TRstride_ENRG),
            grad=self.gradient, curv=self.curvature,
            nadp=self.max_numb_adapt_points,
            ntot=self.max_numb_grid_points,
            n_initial=max(self.numb_grid_points, 2),
        )


Flame.use_temp_profiel_initial_mesh = Flame.use_temp_profile_initial_mesh
