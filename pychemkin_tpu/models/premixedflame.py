"""Premixed laminar flame models (reference premixedflames/premixedflame.py).

``PremixedFlame`` drives the JAX flame core
(:func:`pychemkin_tpu.ops.flame1d.solve_flame`) where the reference
blocks in ``KINPremix_CalculateFlame`` (premixedflame.py:208-229).
Concrete models:

- ``BurnedStabilized_GivenTemperature``  (premixedflame.py:858) — known
  mass flux, temperature profile imposed (TGIV).
- ``BurnedStabilized_EnergyEquation``    (premixedflame.py:877) — known
  mass flux, energy equation solved.
- ``FreelyPropagating``                  (premixedflame.py:920) — mass
  flux is the flame-speed eigenvalue; ``get_flame_speed`` returns
  Su = mdot / rho_unburnt in cm/s (premixedflame.py:605,1004).

(The reference class names spell "BurnedStabilized"; the physical
configuration is the burner-stabilized flame.)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..inlet import Stream, create_stream_from_mixture
from ..logger import logger
from ..mixture import Mixture
from ..ops import flame1d
from .flame import Flame
from .reactormodel import STATUS_FAILED, STATUS_NOT_RUN, STATUS_SUCCESS


class PremixedFlame(Flame):
    """Premixed 1-D flame base (reference premixedflame.py:49)."""

    def __init__(self, inlet: Stream, label: Optional[str] = None):
        if not isinstance(inlet, Stream):
            raise TypeError("the first argument must be a Stream object.")
        if label is None:
            label = "premixedflame"
        # unity flow area makes mass flow rate == mass flux
        # (reference premixedflame.py:63-64)
        if inlet.flowarea <= 0.0:
            inlet.flowarea = 1.0
        super().__init__(inlet, label)
        self._inlet = inlet
        self._final_mass_flow_rate = -1.0
        self.flamespeed = -1.0
        self._solution: Optional[flame1d.FlameSolution] = None
        self._free_flame = False
        self._pinned_T = 400.0
        self._skip_fixed_T = False
        self._auto_T_profile = False
        self._raw_ok = False

    def set_inlet(self, extinlet: Stream):
        """Premixed flame models allow only ONE inlet stream
        (reference premixedflame.py:72-89)."""
        raise ValueError(
            "Premixed flame models do NOT allow a second inlet stream.")

    def unburnt_temperature(self, temperature: float):
        """TUNB (reference premixedflame.py:91)."""
        if temperature <= 200.0:
            logger.error("invalid temperature value.")
            return
        self.temperature = temperature
        self.setkeyword("TUNB", temperature)

    @property
    def mass_flux(self) -> float:
        """Inlet mass flux [g/cm^2-s] = mass flow rate / flow area."""
        return self.mass_flow_rate / self._inlet.flowarea

    # ------------------------------------------------------------------

    def _domain(self):
        if self.ending_x <= self.starting_x:
            raise ValueError(
                "set the domain first: flame.start_position / "
                "flame.end_position (XSTR/XEND)")
        return self.starting_x, self.ending_x

    def _solve(self, energy: str, free_flame: bool, u0=None, x0=None):
        x_start, x_end = self._domain()
        opts = self._flame_solver_options()
        T_fn = self.temperature_profile_fn()
        if energy == "TGIV" and T_fn is None:
            raise ValueError("given-temperature flame needs "
                             "set_temperature_profile (TPRO)")
        xcen = (self.reaction_zone_center_x
                if self.reaction_zone_center_x > x_start else None)
        wmix = (self.reaction_zone_width
                if self.reaction_zone_width > 0 else None)
        if free_flame:
            mdot = None
        else:
            # read the LIVE stream flow (it may have been set after
            # construction); burner flames need a positive mass flux
            self.mass_flow_rate = self._inlet.mass_flowrate
            mdot = self.mass_flux
            if not mdot > 0.0:
                raise ValueError(
                    "burner-stabilized flames need a positive inlet "
                    "mass flow rate (set inlet.mass_flowrate)")
        # explicit initial mesh: the Grid mixin's GRID profile wins;
        # otherwise optionally the TPRO grid (USE_TPRO_GRID)
        x_init = None
        if self.numb_grid_profile >= 2:
            x_init = np.asarray(self.grid_profile)
        elif self.grid_T_profile and self._temp_profile is not None:
            x_init = np.asarray(self._temp_profile[0])
        sol = flame1d.solve_flame(
            self._effective_mech(),
            P=self.pressure, T_in=self.temperature,
            Y_in=np.asarray(self.Y),
            x_start=x_start, x_end=x_end, energy=energy,
            free_flame=free_flame, mdot=mdot,
            T_fix=self._pinned_T,
            su_guess=40.0,
            T_given_fn=T_fn if energy == "TGIV" else None,
            T_init_fn=(T_fn if (energy == "ENRG"
                                and not self._auto_T_profile) else None),
            x_init=x_init,
            xcen=xcen, wmix=wmix,
            skip_fixed_T=self._skip_fixed_T,
            u0=u0, x0=x0,
            **opts)
        return sol

    def run(self) -> int:
        """Run the flame simulation (reference premixedflame.py:334).
        Returns 0 on success."""
        self._free_flame = getattr(self, "_is_free", False)
        energy = "TGIV" if self._energytype == 2 else "ENRG"
        sol = self._solve(energy, self._free_flame)
        self._solution = sol
        self._raw_ok = False
        self._record_solve(success=bool(sol.converged),
                           flame_speed=(float(sol.flame_speed)
                                        if sol.converged else None),
                           **(sol.report or {}))
        if sol.converged:
            self.runstatus = STATUS_SUCCESS
            self._numbsolutionpoints = sol.n_points
            self._final_mass_flow_rate = sol.mdot * self._inlet.flowarea
            return 0
        self.runstatus = STATUS_FAILED
        logger.error("flame simulation failed to converge")
        return 1

    def continuation(self) -> int:
        """Continuation run restarting from the previous solution
        (reference premixedflame.py:430, CNTN keyword) — typically after
        changing pressure/composition/grid controls."""
        if self.runstatus == STATUS_NOT_RUN:
            logger.warning("please run the flame simulation first.")
            return 1
        if self.runstatus != STATUS_SUCCESS or self._solution is None:
            logger.error("previous simulation failed; fix and rerun")
            return 1
        prev = self._solution
        energy = "TGIV" if self._energytype == 2 else "ENRG"
        u0 = flame1d.pack(
            np.asarray(prev.T),
            np.full(prev.x.shape, prev.mdot),
            np.asarray(prev.Y))
        sol = self._solve(energy, self._free_flame, u0=u0, x0=prev.x)
        self._solution = sol
        self._raw_ok = False
        if sol.converged:
            self.runstatus = STATUS_SUCCESS
            self._numbsolutionpoints = sol.n_points
            self._final_mass_flow_rate = sol.mdot * self._inlet.flowarea
            return 0
        self.runstatus = STATUS_FAILED
        return 1

    # --- solution access (reference premixedflame.py:476-856) ----------

    def get_solution_size(self) -> int:
        """Number of grid points in the solution
        (reference premixedflame.py:476)."""
        self._require_solution()
        return self._solution.n_points

    def process_solution(self):
        """Post-process the raw solution (reference
        premixedflame.py:526). Marks the raw data valid for
        ``get_solution_variable_profile`` / ``get_flame_speed``."""
        self._require_solution()
        self._raw_ok = True
        sol = self._solution
        if self._free_flame:
            # the solver already computed Su against the exact unburnt
            # state it solved with; re-deriving it from the (mutable)
            # reactor condition would report a wrong speed if the user
            # tweaked T/P/Y between run() and process_solution()
            self.flamespeed = float(sol.flame_speed)
        if self._TextOut or self._XMLOut:
            self._numbsolutionpoints = len(np.asarray(sol.x))
            raw = {"distance": np.asarray(sol.x),
                   "temperature": np.asarray(sol.T)}
            Y = np.asarray(sol.Y)
            for k, name in enumerate(self._specieslist):
                raw[name] = Y[:, k]
            self._solution_rawarray = raw
            self.write_solution_files()
        return sol


    # --- keyword-surface completions (reference premixedflame.py) -------
    def use_TPRO_grids(self, mode: bool = True):
        """Use the TPRO profile's positions as the initial grid
        (reference premixedflame.py:167 USE_TPRO_GRID)."""
        self.setkeyword("USE_TPRO_GRID", bool(mode))
        self.grid_T_profile = bool(mode)

    def lump_diffusion_imbalance(self, mode: bool = True):
        """Reference premixedflame.py:110: lump the diffusive mass-flux
        imbalance into the LAST species instead of the correction
        velocity. This build's flux assembly enforces sum_k j_k = 0 by
        the correction velocity (the reference's own default); the
        lumping alternative is not implemented, so turning it on warns
        and keeps the correction-velocity formulation."""
        self.setkeyword("LUMP", bool(mode))
        if mode:
            logger.warning("lumped-imbalance closure not implemented; "
                           "keeping the correction-velocity default")

    def set_profilekeywords(self) -> int:
        """Render held profiles into keyword lines (reference
        premixedflame.py:127; the typed solve consumes the profile
        objects directly — this keeps deck rendering in sync)."""
        return self.createkeywordinputlines()[0]

    def set_gridkeywords(self) -> int:
        """(reference premixedflame.py:180)."""
        return self.set_mesh_keywords()

    def create_solution_streams(self):
        """Stream objects for every solution grid point
        (reference premixedflame.py:696). Each carries the local state
        and the flame's mass flux per unit area as its flow rate."""
        self._require_solution()
        sol = self._solution
        from ..inlet import Stream

        streams = []
        Y = np.asarray(sol.Y)
        for i in range(len(np.asarray(sol.x))):
            st = Stream(self.chemistry,
                        label=f"{self.label}-pt{i}")
            st.pressure = self.pressure
            st.temperature = float(np.asarray(sol.T)[i])
            st.Y = Y[i]
            st.mass_flowrate = float(sol.mdot)
            streams.append(st)
        self._solution_mixturearray = streams
        return streams

    def getsolution(self):
        """Alias used throughout the reference docs."""
        return self.process_solution()

    def getrawsolutionstatus(self) -> bool:
        return self._raw_ok

    def get_solution_variable_profile(self, varname: str):
        """Profile of one solution variable over the grid
        (reference premixedflame.py:646). Variables: 'x', 'temperature',
        'mdot', or a species name (mass fraction)."""
        self._require_solution()
        sol = self._solution
        v = varname.strip().lower()
        if v in ("x", "distance", "grid"):
            return np.asarray(sol.x)
        if v in ("t", "temp", "temperature"):
            return np.asarray(sol.T)
        if v in ("mdot", "mass_flux", "massflux"):
            return np.full(sol.x.shape, sol.mdot)
        k = self._effective_mech().species_index(varname)
        return np.asarray(sol.Y[:, k])

    def get_solution_stream_at_grid(self, grid_index: int) -> Stream:
        """Stream at one grid point (reference premixedflame.py:808)."""
        self._require_solution()
        sol = self._solution
        i = int(grid_index)
        if not -sol.n_points <= i < sol.n_points:
            raise IndexError(f"grid index {i} out of range")
        mix = Mixture(self.chemistry)
        mix.pressure = self.pressure
        mix.temperature = float(sol.T[i])
        mix.Y = np.asarray(sol.Y[i])
        out = create_stream_from_mixture(mix, label=f"{self.label}@{i}")
        out.mass_flowrate = sol.mdot * self._inlet.flowarea
        out.flowarea = self._inlet.flowarea
        return out

    def get_solution_stream(self, x: float) -> Stream:
        """Stream interpolated at position x (reference
        premixedflame.py:757)."""
        self._require_solution()
        sol = self._solution
        if not sol.x[0] <= x <= sol.x[-1]:
            raise ValueError(f"x={x} outside the solution domain")
        mix = Mixture(self.chemistry)
        mix.pressure = self.pressure
        mix.temperature = float(np.interp(x, sol.x, sol.T))
        Y = np.array([np.interp(x, sol.x, sol.Y[:, k])
                      for k in range(sol.Y.shape[1])])
        mix.Y = np.clip(Y, 0.0, None)
        out = create_stream_from_mixture(mix, label=f"{self.label}@x={x}")
        out.mass_flowrate = sol.mdot * self._inlet.flowarea
        out.flowarea = self._inlet.flowarea
        return out

    def _require_solution(self):
        if self.runstatus == STATUS_NOT_RUN or self._solution is None:
            raise RuntimeError("please run the flame simulation first.")
        if self.runstatus != STATUS_SUCCESS:
            raise RuntimeError("simulation failed; no solution available")


class BurnedStabilized_GivenTemperature(PremixedFlame):
    """Burner-stabilized flame with an imposed temperature profile
    (reference premixedflame.py:858): known inlet mass flux, TGIV."""

    def __init__(self, inlet: Stream, label: Optional[str] = None):
        super().__init__(inlet, label or "Premixed Burner GivenT")
        self._energytype = 2
        self.setkeyword("BURN", True)
        self.setkeyword("TGIV", True)
        self._is_free = False


class BurnedStabilized_EnergyEquation(PremixedFlame):
    """Burner-stabilized flame solving the energy equation
    (reference premixedflame.py:877)."""

    def __init__(self, inlet: Stream, label: Optional[str] = None):
        super().__init__(inlet, label or "Premixed Burner Energy")
        self._energytype = 1
        self.setkeyword("BURN", True)
        self.setkeyword("ENRG", True)
        self._is_free = False

    def skip_fix_T_solution(self, mode: bool = True):
        """NOFT — skip the fixed-temperature intermediate solve
        (reference premixedflame.py:894)."""
        self._skip_fixed_T = bool(mode)
        self.setkeyword("NOFT", mode)

    def automatic_temperature_profile_estimate(self, mode: bool = True):
        """TPROF — build the initial temperature estimate from the
        equilibrium state (reference premixedflame.py:906). This is the
        default behavior of the TPU solver core."""
        self._auto_T_profile = bool(mode)
        self.setkeyword("TPROF", mode)


class FreelyPropagating(PremixedFlame):
    """Freely-propagating premixed flame — computes the laminar flame
    speed as the mass-flux eigenvalue (reference premixedflame.py:920)."""

    def __init__(self, inlet: Stream, label: Optional[str] = None):
        super().__init__(inlet, label or "Premixed Propagating")
        self._energytype = 1
        self._flamemode = 0
        self.setkeyword("FREE", True)
        self.setkeyword("ENRG", True)
        self._is_free = True
        self.flamespeed = -1.0

    def skip_fix_T_solution(self, mode: bool = True):
        """NOFT (reference premixedflame.py:937)."""
        self._skip_fixed_T = bool(mode)
        self.setkeyword("NOFT", mode)

    def automatic_temperature_profile_estimate(self, mode: bool = True):
        """TPROF (reference premixedflame.py:949). When ON, the initial
        temperature estimate comes from the equilibrium state (which is
        also this build's default construction) and any user-pinned
        temperature reverts to the default anchor."""
        self._auto_T_profile = bool(mode)
        self.setkeyword("TPROF", mode)
        if not mode:
            return
        if "TFIX" in self._keywords:
            logger.warning("auto temperature profile option is ON, "
                           "the pinned temperature is ignored.")
            self.removekeyword("TFIX")
            self._pinned_T = 400.0

    def pinned_temperature(self, temperature: float = 400.0):
        """TFIX — anchor the flame by pinning this temperature to the
        mesh (reference premixedflame.py:973). Must exceed the unburnt
        gas temperature and sit below the ignition temperature."""
        if temperature <= self.temperature:
            raise ValueError(
                "pinned temperature must exceed the unburnt temperature")
        if self._auto_T_profile:
            raise ValueError("auto temperature profile option is ON; "
                             "the pinned temperature would be ignored "
                             "(reference premixedflame.py:991)")
        self._pinned_T = float(temperature)
        self.setkeyword("TFIX", float(temperature))

    def get_flame_speed(self) -> float:
        """Laminar flame speed [cm/s] (reference premixedflame.py:1004).
        Requires ``process_solution()`` first; returns 0.0 otherwise."""
        if not self.getrawsolutionstatus():
            logger.info("please use 'getsolution' method to post-process "
                        "the raw solution data first.")
            return 0.0
        return self.flamespeed
