"""Open-reactor base and the perfectly-stirred-reactor (PSR) family.

TPU-native re-implementation of the reference's steady-state stirred
reactors (reference: src/ansys/chemkin/stirreactors/openreactor.py and
stirreactors/PSR.py): the multi-inlet registry, the
``perfectlystirredreactor`` base with equilibrium-based initial
estimates, and the four concrete variants

- ``PSR_SetResTime_EnergyConservation``   (PSR.py:866)
- ``PSR_SetVolume_EnergyConservation``    (PSR.py:1021)
- ``PSR_SetResTime_FixedTemperature``     (PSR.py:1176)
- ``PSR_SetVolume_FixedTemperature``      (PSR.py:1205)

The reference marshals inlets and reactor state into the native library
and blocks in a TWOPNT-class solve (PSR.py:233/:523/:640); here ``run()``
combines the inlets on the host (mass-flow-weighted composition and
enthalpy — the same mixing the native solver performs) and calls the
batched Newton/pseudo-transient kernel
:func:`pychemkin_tpu.ops.psr.solve_psr`. ``run_sweep`` evaluates a whole
residence-time S-curve as one vmapped solve.

``process_solution()`` returns the exit :class:`Stream`
(reference: PSR.py:787-865, KINAll0D_GetExitMassFlowRate).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..inlet import Stream
from ..logger import logger
from ..mixture import Mixture, equilibrium
from ..ops import psr as psr_ops
from ..resilience.status import name_of as status_name_of
from ..ops import thermo
from .reactormodel import (
    STATUS_FAILED,
    STATUS_SUCCESS,
    ReactorModel,
)
from .steadystatesolver import SteadyStateSolver


class openreactor(ReactorModel, SteadyStateSolver):
    """Steady-state open reactor: external-inlet registry
    (reference: stirreactors/openreactor.py:38)."""

    def __init__(self, reactor_condition: Mixture, label: str):
        ReactorModel.__init__(self, reactor_condition, label)
        SteadyStateSolver.__init__(self)
        self._inlets: Dict[str, Stream] = {}

    def set_inlet(self, inlet: Stream, name: Optional[str] = None):
        """Register an inlet stream. Re-using a name REPLACES that inlet;
        distinct names accumulate mass flow
        (reference: openreactor.py:90-165)."""
        if not isinstance(inlet, Stream):
            raise TypeError("inlet must be a Stream")
        if inlet.chemID != self.chemID:
            raise ValueError("inlet must share the reactor's chemistry set")
        key = name if name else (inlet.label or f"inlet{len(self._inlets)}")
        if key in self._inlets:
            logger.warning("inlet %r replaced", key)
        self._inlets[key] = inlet

    def reset_inlet(self, inlet: Stream, name: str):
        """Replace a registered inlet (reference: openreactor.py:166)."""
        if name not in self._inlets:
            raise KeyError(f"no inlet named {name!r}")
        self._inlets[name] = inlet

    def remove_inlet(self, name: str):
        """(reference: openreactor.py:203)."""
        if name not in self._inlets:
            raise KeyError(f"no inlet named {name!r}")
        del self._inlets[name]

    @property
    def inlet_names(self) -> List[str]:
        return list(self._inlets.keys())

    @property
    def numbinlets(self) -> int:
        return len(self._inlets)


    def number_external_inlets(self) -> int:
        """(reference openreactor.py: count of registered inlets)."""
        return len(self._inlets)

    def net_vol_flowrate(self) -> float:
        """Net external volumetric inflow [cm^3/s]
        (reference openreactor.py:271)."""
        return float(sum(s.convert_to_vol_flowrate()
                         for s in self._inlets.values()))

    def net_mass_flowrate(self) -> float:
        """Total inlet mass flow [g/s] (reference: openreactor.py:259)."""
        return sum(s.convert_to_mass_flowrate()
                   for s in self._inlets.values())

    def combined_inlet(self) -> Tuple[np.ndarray, float, float]:
        """(Y_in [KK], h_in [erg/g], mdot [g/s]) — mass-flow-weighted
        mixture of all inlets, the stream mixing the native solver performs
        from its per-inlet inputs (reference: PSR.py:203-285)."""
        if not self._inlets:
            raise RuntimeError("no inlet streams registered")
        mdots = np.array([s.convert_to_mass_flowrate()
                          for s in self._inlets.values()])
        total = mdots.sum()
        if total <= 0.0:
            raise RuntimeError("total inlet mass flow is zero")
        w = mdots / total
        Y_in = np.zeros(self.numbspecies)
        h_in = 0.0
        for wi, s in zip(w, self._inlets.values()):
            Y_in += wi * s.Y
            h_in += wi * float(thermo.mixture_enthalpy_mass(
                self.mech, s.temperature, jnp.asarray(s.Y)))
        return Y_in, h_in, float(total)


class perfectlystirredreactor(openreactor):
    """Steady-state PSR base (reference: PSR.py:48). Constructed from a
    GUESSED mixture/stream — its state seeds the Newton iteration, as in
    the reference where the construction mixture provides the initial
    solution estimate."""

    #: specification mode ("tau" | "vol") and energy ("ENRG" | "TGIV")
    mode = psr_ops.MODE_TAU
    energy_type = "ENRG"

    def __init__(self, guessedmixture: Mixture, label: Optional[str] = None):
        super().__init__(guessedmixture, label or "PSR")
        self._tau = 0.0
        self._tauset = False
        self._volume = guessedmixture.volume
        self._volumeset = False
        self._qloss = 0.0
        self._reactor_index = 1
        self._estimate_T: Optional[float] = None
        self._estimate_Y: Optional[np.ndarray] = None
        self._solution: Optional[psr_ops.PSRSolution] = None

    # --- specification (reference: PSR.py:173-202) -------------------------
    @property
    def residence_time(self) -> float:
        """tau [s] (reference: PSR.py:173)."""
        return self._tau

    @residence_time.setter
    def residence_time(self, value: float):
        if value <= 0.0:
            raise ValueError("residence time must be positive")
        self._tau = float(value)
        self._tauset = True
        self._record_keyword("TAU", float(value))

    @property
    def volume(self) -> float:
        return self._volume

    @volume.setter
    def volume(self, value: float):
        if value <= 0.0:
            raise ValueError("volume must be positive")
        self._volume = float(value)
        self._volumeset = True
        self._record_keyword("VOL", float(value))

    @property
    def heat_loss_rate(self) -> float:
        """QLOS [erg/s]."""
        return self._qloss

    @heat_loss_rate.setter
    def heat_loss_rate(self, value: float):
        self._qloss = float(value)
        self._record_keyword("QLOS", float(value))

    def set_reactor_index(self, index: int):
        """Cluster position for reactor networks
        (reference: PSR.py:286)."""
        self._reactor_index = int(index)

    # --- initial estimates (reference: PSR.py:301-426) ---------------------

    def set_inlet_keywords(self) -> int:
        """Render the inlet registry into keyword lines (reference
        PSR.py:203 -> KINAll0D_SetupPSRInletInputs; the typed solve
        mixes the inlets directly — this keeps decks in sync)."""
        for name, st in self._inlets.items():
            self._record_keyword(f"INLET_{name}".upper(),
                                 float(st.convert_to_mass_flowrate()))
        return 0

    def cluster_process_keywords(self) -> int:
        """Prepare this reactor for a cluster solve (reference
        PSR.py:464): route any full-keyword deck state and render the
        keyword tables; the coupled solve itself happens in
        ReactorNetwork.run_cluster."""
        self.consume_protected_keywords()
        self.set_SSsolver_keywords()
        return self.set_inlet_keywords()

    def set_estimate_conditions(self, temperature: Optional[float] = None,
                                mixture: Optional[Mixture] = None,
                                use_equilibrium: bool = True):
        """Set the Newton initial estimate: an explicit (T, mixture), or
        the constant-pressure equilibrium of the combined inlet
        (reference: PSR.py:301 uses the native equilibrium the same way)."""
        if mixture is not None:
            self._estimate_Y = mixture.Y
            self._estimate_T = (temperature if temperature
                                else mixture.temperature)
            return
        if temperature is not None:
            self._estimate_T = float(temperature)
        if use_equilibrium and self._inlets:
            Y_in, _, _ = self.combined_inlet()
            first = next(iter(self._inlets.values()))
            guess = Mixture(self.chemistry)
            guess.pressure = self.pressure
            guess.temperature = first.temperature
            guess.Y = Y_in
            eq = equilibrium(guess, opt=5)
            self._estimate_Y = eq.Y
            if temperature is None:
                self._estimate_T = eq.temperature

    def reset_estimate_temperature(self, temperature: float):
        """(reference: PSR.py:367)."""
        self._estimate_T = float(temperature)

    def reset_estimate_composition(self, mixture: Mixture):
        """(reference: PSR.py:394)."""
        self._estimate_Y = mixture.Y

    def _guess(self) -> Tuple[float, np.ndarray]:
        T = (self._estimate_T if self._estimate_T is not None
             else self._condition.temperature)
        Y = (self._estimate_Y if self._estimate_Y is not None
             else self._condition.Y)
        return float(T), np.asarray(Y)

    # --- solve -------------------------------------------------------------
    def validate_inputs(self) -> int:
        if self.mode == psr_ops.MODE_TAU and not self._tauset:
            logger.error("residence time is required (TAU)")
            return 1
        if self.mode == psr_ops.MODE_VOLUME and not self._volumeset:
            logger.error("reactor volume is required (VOL)")
            return 2
        if not self._inlets:
            logger.error("at least one inlet stream is required")
            return 3
        return 0

    def _solve_kwargs(self):
        Y_in, h_in, mdot = self.combined_inlet()
        return dict(
            mech=self._effective_mech(),
            mode=self.mode,
            energy=self.energy_type,
            P=self.pressure,
            Y_in=jnp.asarray(Y_in),
            h_in=h_in,
            mdot=mdot,
            qloss=self._qloss,
            T_fixed=self._condition.temperature,
            ss_atol=self.SSabsolute_tolerance,
            ss_rtol=self.SSrelative_tolerance,
            n_newton=self.SSmaxiteration // 2,
            n_pseudo=self.TRnumbsteps_ENRG if self.energy_type == "ENRG"
            else self.TRnumbsteps_fixT,
            pseudo_dt0=self.TRstride_ENRG if self.energy_type == "ENRG"
            else self.TRstride_fixT,
            pseudo_up=self.TRupfactor,
            pseudo_down=self.TRdownfactor,
            pseudo_dt_min=self.TRminstepsize,
            pseudo_dt_max=self.TRmaxstepsize,
            T_max=self.maxTbound,
            species_floor=self.speciesfloor,
        )

    def run(self) -> int:
        """Solve the steady state (reference: PSR.py:643-786)."""
        self.consume_protected_keywords()
        if self.validate_inputs() != 0:
            self.runstatus = STATUS_FAILED
            return self.runstatus
        T_g, Y_g = self._guess()
        t0 = time.perf_counter()
        sol = psr_ops.solve_psr(
            tau=self._tau, volume=self._volume,
            T_guess=jnp.asarray(T_g), Y_guess=jnp.asarray(Y_g),
            **self._solve_kwargs())
        self._solution = jax.device_get(sol)
        ok = bool(self._solution.converged)
        status = int(self._solution.status)
        self.runstatus = STATUS_SUCCESS if ok else STATUS_FAILED
        self._record_solve(
            wall_s=round(time.perf_counter() - t0, 6), success=ok,
            status=status, status_name=status_name_of(status),
            n_newton=int(self._solution.n_newton),
            n_newton_direct=(int(self._solution.n_newton_direct)
                             if self._solution.n_newton_direct is not None
                             else None),
            n_newton_polish=(int(self._solution.n_newton_polish)
                             if self._solution.n_newton_polish is not None
                             else None),
            residual=float(self._solution.residual),
            energy=self.energy_type, mode=self.mode)
        if not ok:
            logger.error("PSR steady-state solve did not converge "
                         "(residual %.2e)", float(self._solution.residual))
        else:
            # warm-start the next run from this solution, as the
            # reference's continuation workflows do (PSR.py:367-426)
            self._estimate_T = float(self._solution.T)
            self._estimate_Y = np.asarray(self._solution.Y)
        return self.runstatus

    def run_sweep(self, taus=None, volumes=None, *, chunk_size=None,
                  checkpoint_path=None, job_report=None,
                  driver_kwargs=None):
        """Whole S-curve in ONE vmapped solve — the TPU replacement for
        the reference's serial continuation loop
        (examples/PSR/PSRgas.py:252-255). All elements share this
        reactor's inlets and estimate. Returns (T [B], Y [B, KK],
        converged [B], status [B]).

        The sweep runs under the durable-job driver: ``chunk_size``
        splits the S-curve into sequential same-shape jitted calls,
        ``checkpoint_path`` banks every completed chunk atomically
        (preemption-safe; resumes on any later device count), and
        ``job_report`` (a dict) receives the driver's
        :class:`~pychemkin_tpu.resilience.driver.SweepJobReport`
        fields."""
        from ..resilience import checkpoint as _checkpoint
        from ..resilience import driver as _driver

        T_g, Y_g = self._guess()
        kwargs = self._solve_kwargs()
        if self.mode == psr_ops.MODE_TAU:
            if taus is None:
                raise ValueError("taus required for SetResTime sweeps")
            params = jnp.asarray(taus, jnp.float64)

            def one(p):
                return psr_ops.solve_psr(
                    tau=p, volume=self._volume,
                    T_guess=jnp.asarray(T_g), Y_guess=jnp.asarray(Y_g),
                    **kwargs)
        else:
            if volumes is None:
                raise ValueError("volumes required for SetVolume sweeps")
            params = jnp.asarray(volumes, jnp.float64)

            def one(p):
                return psr_ops.solve_psr(
                    tau=self._tau, volume=p,
                    T_guess=jnp.asarray(T_g), Y_guess=jnp.asarray(Y_g),
                    **kwargs)

        vm = jax.vmap(one)
        B = int(params.shape[0])

        sig = None
        if checkpoint_path is not None:
            sig = _checkpoint.config_signature(
                "psr.run_sweep", type(self).__name__, self.mode,
                self._volume, self._tau,
                cfg={k: v for k, v in kwargs.items() if k != "mech"},
                arrays=(params, np.asarray(T_g), np.asarray(Y_g)),
                tree=kwargs["mech"])

        def index_solve(idx):
            sol = vm(params[idx])
            return {"T": sol.T, "Y": sol.Y,
                    "converged": sol.converged, "status": sol.status}

        results, _report = _driver.run_vmapped_sweep_job(
            index_solve, B, chunk_size=chunk_size,
            checkpoint_path=checkpoint_path, signature=sig,
            result_keys=("T", "Y", "converged", "status"),
            job_report=job_report, label="psr.run_sweep",
            **(driver_kwargs or {}))
        return (results["T"], results["Y"], results["converged"],
                results["status"])

    # --- solution (reference: PSR.py:787-865) ------------------------------
    def process_solution(self) -> Stream:
        """Exit stream at the solved state; carries the exit mass flow
        (== total inlet flow at steady state,
        reference: KINAll0D_GetExitMassFlowRate, PSR.py:845)."""
        if self._solution is None:
            raise RuntimeError("run() the reactor first")
        sol = self._solution
        out = Stream(self.chemistry, label=f"{self.label}-exit")
        out.pressure = self.pressure
        out.temperature = float(sol.T)
        out.Y = np.asarray(sol.Y)
        out.mass_flowrate = self.net_mass_flowrate()
        self._numbsolutionpoints = 1
        self._solution_rawarray = {
            "temperature": np.asarray([sol.T]),
            "pressure": np.asarray([self.pressure]),
            "volume": np.asarray([sol.volume]),
            "flowrate": np.asarray([self.net_mass_flowrate()]),
        }
        Y = np.asarray(sol.Y)
        for k, name in enumerate(self._specieslist):
            self._solution_rawarray[name] = Y[k:k + 1]
        if self._TextOut or self._XMLOut:
            self.write_solution_files()
        return out

    @property
    def exit_residence_time(self) -> float:
        """Actual residence time of the solved state."""
        if self._solution is None:
            raise RuntimeError("run() the reactor first")
        return float(self._solution.tau)

    @property
    def solved_volume(self) -> float:
        if self._solution is None:
            raise RuntimeError("run() the reactor first")
        return float(self._solution.volume)


class PSR_SetResTime_EnergyConservation(perfectlystirredreactor):
    """Given residence time + energy equation (reference: PSR.py:866)."""

    mode = psr_ops.MODE_TAU
    energy_type = "ENRG"


class PSR_SetVolume_EnergyConservation(perfectlystirredreactor):
    """Given volume + energy equation (reference: PSR.py:1021)."""

    mode = psr_ops.MODE_VOLUME
    energy_type = "ENRG"


class PSR_SetResTime_FixedTemperature(perfectlystirredreactor):
    """Given residence time + given temperature
    (reference: PSR.py:1176)."""

    mode = psr_ops.MODE_TAU
    energy_type = "TGIV"


class PSR_SetVolume_FixedTemperature(perfectlystirredreactor):
    """Given volume + given temperature (reference: PSR.py:1205)."""

    mode = psr_ops.MODE_VOLUME
    energy_type = "TGIV"
