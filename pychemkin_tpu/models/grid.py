"""1-D adaptive-grid quality controls (the reference's Grid mixin).

TPU-native counterpart of reference src/ansys/chemkin/grid.py:33 — the
mesh-keyword surface shared by every 1-D steady flame model: initial and
maximum point counts (NPTS/NTOT), domain bounds (XSTR/XEND), reaction
zone estimate (XCEN/WMIX), adaption budget (NADP) and the GRAD/CURV
solution-quality ratios consumed by
:func:`pychemkin_tpu.ops.flame1d.refine_grid`.
"""

from __future__ import annotations

import numpy as np

from ..logger import logger


class Grid:
    """Grid quality control parameters for 1-D steady-state models
    (reference grid.py:38-60 defaults)."""

    def __init__(self):
        self.max_numb_grid_points = 250       # NTOT
        self.max_numb_adapt_points = 10       # NADP
        self.gradient = 0.1                   # GRAD
        self.curvature = 0.5                  # CURV
        self.numb_grid_points = 6             # NPTS
        self.starting_x = 0.0                 # XSTR
        self.ending_x = 0.0                   # XEND
        self.reaction_zone_center_x = 0.0     # XCEN
        self.reaction_zone_width = 0.0        # WMIX
        self.grid_profile: list = []          # explicit GRID x values
        self.numb_grid_profile = 0

    def set_numb_grid_points(self, numb_points: int):
        """Initial uniform grid points (reference grid.py:54)."""
        if numb_points > 0:
            self.numb_grid_points = int(numb_points)
        else:
            logger.error("number of points must > 0.")

    def set_max_grid_points(self, numb_points: int):
        """Cap on points during refinement (reference grid.py:70)."""
        if numb_points > 0:
            self.max_numb_grid_points = int(numb_points)
        else:
            logger.error("number of points must > 0.")

    @property
    def start_position(self) -> float:
        """Coordinate of the first grid point [cm] (reference
        grid.py:87)."""
        return self.starting_x

    @start_position.setter
    def start_position(self, position: float):
        self.starting_x = float(position)

    @property
    def end_position(self) -> float:
        """Coordinate of the last grid point [cm] (reference
        grid.py:111)."""
        return self.ending_x

    @end_position.setter
    def end_position(self, position: float):
        self.ending_x = float(position)

    def set_reaction_zone_center(self, position: float):
        """XCEN — estimated flame-front location (reference
        grid.py:139)."""
        self.reaction_zone_center_x = float(position)

    def set_reaction_zone_width(self, size: float):
        """WMIX — estimated mixing-zone width (reference grid.py:159)."""
        self.reaction_zone_width = float(size)

    def set_max_adaptive_points(self, numb_points: int):
        """NADP — points added per adaption pass (reference
        grid.py:175)."""
        if numb_points > 0:
            self.max_numb_adapt_points = int(numb_points)
        else:
            logger.error("number of points must > 0.")

    def set_solution_quality(self, gradient: float = 0.1,
                             curvature: float = 0.5):
        """GRAD/CURV adaption ratios (reference grid.py:201): an interval
        is refined when a component's jump exceeds ``gradient`` times its
        range or its slope jump exceeds ``curvature`` times the slope
        range."""
        if not 0.0 < gradient <= 1.0 or not 0.0 < curvature <= 1.0:
            logger.error("GRAD/CURV must be in (0, 1].")
            return
        self.gradient = float(gradient)
        self.curvature = float(curvature)

    def set_grid_profile(self, mesh) -> int:
        """Explicit initial mesh (reference grid.py:239 ``GRID x``
        profile). Overrides NPTS when set."""
        mesh = np.asarray(mesh, dtype=np.float64)
        if mesh.ndim != 1 or mesh.size < 2:
            logger.error("grid profile needs >= 2 points")
            return 1
        if not np.all(np.diff(mesh) > 0):
            logger.error("grid profile must be strictly increasing")
            return 1
        self.grid_profile = list(map(float, mesh))
        self.numb_grid_profile = mesh.size
        self.starting_x = float(mesh[0])
        self.ending_x = float(mesh[-1])
        return 0
