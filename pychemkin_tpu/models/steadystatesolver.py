"""Steady-state solver controls (mixin).

TPU-native re-implementation of the reference's ``SteadyStateSolver``
mixin (reference: src/ansys/chemkin/steadystatesolver.py:35-483): the
damped-Newton + pseudo-transient continuation control parameters, with
the reference's defaults (:40-99). In the reference these populate the
``SSsolverkeywords`` dict marshalled into the native TWOPNT-class solver;
here they parameterize :func:`pychemkin_tpu.ops.psr.solve_psr` (and the
flame solver) directly. Setter names and keyword spellings are preserved.
"""

from __future__ import annotations

from typing import Dict, Tuple, Union


class SteadyStateSolver:
    """Mixin holding steady-state solver control parameters
    (reference: steadystatesolver.py:35)."""

    def __init__(self):
        # steady-state search (reference defaults :40-67)
        self.SSabsolute_tolerance = 1.0e-9
        self.SSrelative_tolerance = 1.0e-4
        self.SSmaxiteration = 100
        self.SSJacobianage = 20
        self.maxpseudotransient = 100
        self.numbinitialpseudosteps = 0
        self.maxTbound = 5000.0
        self.speciesfloor = -1.0e-14
        self.species_positive = 0.0
        self.use_legacy_technique = False
        self.SSdamping = 1
        self.absolute_perturbation = 0.0
        self.relative_perturbation = 0.0
        # pseudo-transient stepping (reference defaults :69-95)
        self.TRabsolute_tolerance = 1.0e-9
        self.TRrelative_tolerance = 1.0e-4
        self.TRmaxiteration = 25
        self.timestepsizeage = 25
        self.TRminstepsize = 1.0e-10
        self.TRmaxstepsize = 1.0e-2
        self.TRupfactor = 2.0
        self.TRdownfactor = 2.2
        self.TRJacobianage = 20
        self.TRstride_fixT = 1.0e-6
        self.TRnumbsteps_fixT = 100
        self.TRstride_ENRG = 1.0e-6
        self.TRnumbsteps_ENRG = 100
        self.print_level = 1
        self.SSsolverkeywords: Dict[str, Union[int, float, str, bool]] = {}

    # --- tolerance properties (reference: :102-194) ------------------------
    @property
    def steady_state_tolerances(self) -> Tuple[float, float]:
        return self.SSabsolute_tolerance, self.SSrelative_tolerance

    @steady_state_tolerances.setter
    def steady_state_tolerances(self, tolerances: Tuple[float, float]):
        atol, rtol = tolerances
        if atol <= 0.0 or rtol <= 0.0:
            raise ValueError("tolerances must be positive")
        self.SSabsolute_tolerance = float(atol)
        self.SSrelative_tolerance = float(rtol)
        self.SSsolverkeywords["ATOL"] = float(atol)
        self.SSsolverkeywords["RTOL"] = float(rtol)

    @property
    def time_stepping_tolerances(self) -> Tuple[float, float]:
        return self.TRabsolute_tolerance, self.TRrelative_tolerance

    @time_stepping_tolerances.setter
    def time_stepping_tolerances(self, tolerances: Tuple[float, float]):
        atol, rtol = tolerances
        if atol <= 0.0 or rtol <= 0.0:
            raise ValueError("tolerances must be positive")
        self.TRabsolute_tolerance = float(atol)
        self.TRrelative_tolerance = float(rtol)
        self.SSsolverkeywords["ATIM"] = float(atol)
        self.SSsolverkeywords["RTIM"] = float(rtol)

    # --- iteration/continuation controls (reference: :195-263) -------------
    def set_max_pseudo_transient_call(self, maxtime: int):
        self.maxpseudotransient = int(maxtime)
        self.SSsolverkeywords["MAXTIME"] = int(maxtime)

    def set_max_timestep_iteration(self, maxiteration: int):
        self.TRmaxiteration = int(maxiteration)
        self.SSsolverkeywords["TRMI"] = int(maxiteration)

    def set_max_search_iteration(self, maxiteration: int):
        self.SSmaxiteration = int(maxiteration)
        self.SSsolverkeywords["SSMI"] = int(maxiteration)

    def set_initial_timesteps(self, initsteps: int):
        self.numbinitialpseudosteps = int(initsteps)
        self.SSsolverkeywords["NINIT"] = int(initsteps)

    # --- bounds (reference: :265-315) --------------------------------------
    def set_species_floor(self, floor_value: float):
        self.speciesfloor = float(floor_value)
        self.SSsolverkeywords["SFLR"] = float(floor_value)

    def set_temperature_ceiling(self, ceilingvalue: float):
        if ceilingvalue <= 0.0:
            raise ValueError("temperature ceiling must be positive")
        self.maxTbound = float(ceilingvalue)
        self.SSsolverkeywords["TMAX"] = float(ceilingvalue)

    def set_species_reset_value(self, resetvalue: float):
        self.species_positive = float(resetvalue)
        self.SSsolverkeywords["SPOS"] = float(resetvalue)

    # --- pseudo-timestep sizing (reference: :317-400) ----------------------
    def set_max_pseudo_timestep_size(self, dtmax: float):
        self.TRmaxstepsize = float(dtmax)
        self.SSsolverkeywords["DTMX"] = float(dtmax)

    def set_min_pseudo_timestep_size(self, dtmin: float):
        self.TRminstepsize = float(dtmin)
        self.SSsolverkeywords["DTMN"] = float(dtmin)

    def set_pseudo_timestep_age(self, age: int):
        self.timestepsizeage = int(age)
        self.SSsolverkeywords["STPAGE"] = int(age)

    def set_Jacobian_age(self, age: int):
        self.SSJacobianage = int(age)
        self.SSsolverkeywords["NJAC"] = int(age)

    def set_pseudo_Jacobian_age(self, age: int):
        self.TRJacobianage = int(age)
        self.SSsolverkeywords["TJAC"] = int(age)

    # --- options (reference: :402-483) -------------------------------------
    def set_damping_option(self, ON: bool):
        self.SSdamping = 1 if ON else 0
        self.SSsolverkeywords["DAMP"] = bool(ON)

    def set_legacy_option(self, ON: bool):
        self.use_legacy_technique = bool(ON)

    def set_print_level(self, level: int):
        self.print_level = int(max(0, min(2, level)))
        self.SSsolverkeywords["PRNT"] = self.print_level

    def set_pseudo_timestepping_parameters(self, energymode: bool,
                                           numbsteps: int, stride: float):
        """Initial stride/steps per pseudo-transient call (reference:
        :458; separate settings for ENRG and fixed-T problems)."""
        if energymode:
            self.TRnumbsteps_ENRG = int(numbsteps)
            self.TRstride_ENRG = float(stride)
        else:
            self.TRnumbsteps_fixT = int(numbsteps)
            self.TRstride_fixT = float(stride)
        self.SSsolverkeywords["TIME" if not energymode else "TIM2"] = (
            int(numbsteps), float(stride))


    def set_SSsolver_keywords(self):
        """Mirror the accumulated steady-state solver parameters into
        the model's keyword table (reference flame.py:245 /
        PSR.py keyword marshalling; here the typed solve consumes the
        attributes directly, so this keeps decks and
        createkeywordinputlines in sync)."""
        for k, v in self.SSsolverkeywords.items():
            self._record_keyword(k, v)
