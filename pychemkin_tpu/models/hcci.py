"""Single/multi-zone HCCI engine model (reference engines/HCCI.py:48).

``HCCIengine`` mirrors the reference's zonal configuration surface —
per-zone temperature / volume fraction / mass fraction / heat-transfer
area / composition or equivalence-ratio setup (HCCI.py:172-557) and the
energy-equation switch CA (HCCI.py:559) — and drives the multi-zone
uniform-pressure kernel :func:`pychemkin_tpu.ops.engine.solve_hcci`
where the reference blocks in ``KINAll0D_SetupHCCIInputs`` /
``SetupHCCIZoneInputs`` (chemkin_wrapper.py:668-672). The zone axis is
the SURVEY §2.3 second parallel dimension: zones integrate as one
stacked state and an (RPM, CR, phi, T) sweep vmaps over engines.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..logger import logger
from ..mixture import Mixture
from ..ops import engine as engine_ops
from ..resilience.status import name_of as status_name_of
from .engine import Engine
from .reactormodel import STATUS_FAILED, STATUS_SUCCESS, Keyword


class HCCIengine(Engine):
    """Single- or multi-zone homogeneous-charge compression-ignition
    engine (reference HCCI.py:48)."""

    def __init__(self, reactor_condition: Mixture, label: str = "",
                 nzones: Optional[int] = None):
        if nzones is None:
            nzones = 1
        if label == "":
            label = "HCCI" if nzones == 1 else "Multi-Zone HCCI"
        super().__init__(reactor_condition, label)
        self._nzones = int(nzones)
        if self._nzones > 1:
            # the reference REQUIRES full-keyword mode for multi-zone
            # simulations and flips the class-level flag itself
            # (HCCI.py:95-96); mirrored for deck parity — the typed
            # zonal API keeps working either way
            Keyword.setfullkeywords(True)
        # zonal setup mode (reference HCCI.py:98-101):
        # 0 uniform, 1 raw mole fractions, 2 equivalence ratio
        self._zonalsetupmode = 0
        self.zonetemperature: List[float] = []
        self.zonevolume: List[float] = []
        self.usezonemass = False
        self.zonemass: List[float] = []
        self.zoneHTarea: List[float] = []
        self.zonemolefractions: List[np.ndarray] = []
        self._fuel_recipe = None
        self._oxid_recipe = None
        self._product_names: List[str] = []
        self.zonephi: List[float] = []
        self._energy_switch_CA: Optional[float] = None

    def get_number_of_zones(self) -> int:
        """(reference HCCI.py:161)."""
        return self._nzones

    def _check_zonal(self, values, what: str) -> bool:
        if len(values) != self._nzones:
            logger.error("%s needs one value per zone (%d)", what,
                         self._nzones)
            return False
        return True

    def set_zonal_temperature(self, zonetemp: List[float]):
        """(reference HCCI.py:172)."""
        if self._check_zonal(zonetemp, "zonal temperature"):
            self.zonetemperature = [float(t) for t in zonetemp]

    def set_zonal_volume_fraction(self, zonevol: List[float]):
        """(reference HCCI.py:211)."""
        if self._check_zonal(zonevol, "zonal volume fraction"):
            self.zonevolume = [float(v) for v in zonevol]

    def set_zonal_mass_fraction(self, zonemass: List[float]):
        """(reference HCCI.py:251). Overrides any volume-fraction split:
        the volume partition follows from the zonal ideal-gas states at
        IVC (V_i = m_i / rho_i at the shared pressure)."""
        if self._check_zonal(zonemass, "zonal mass fraction"):
            self.usezonemass = True
            self.zonemass = [float(m) for m in zonemass]

    def set_zonal_heat_transfer_area_fraction(self, zonearea: List[float]):
        """(reference HCCI.py:293)."""
        if self._check_zonal(zonearea, "zonal HT area fraction"):
            self.zoneHTarea = [float(a) for a in zonearea]

    def set_zonal_gas_mole_fractions(self, zonemolefrac):
        """Per-zone raw mole fractions [NZ, KK]
        (reference HCCI.py:333)."""
        arr = [np.asarray(z, dtype=np.float64) for z in zonemolefrac]
        if self._check_zonal(arr, "zonal mole fractions"):
            self.zonemolefractions = arr
            self._zonalsetupmode = 1

    def define_fuel_composition(self, recipe):
        """(reference HCCI.py:377)."""
        self._fuel_recipe = recipe

    def define_oxid_composition(self, recipe):
        """(reference HCCI.py:396)."""
        self._oxid_recipe = recipe

    def define_product_composition(self, products: List[str]):
        """(reference HCCI.py:415)."""
        self._product_names = list(products)

    def set_zonal_equivalence_ratio(self, zonephi: List[float]):
        """(reference HCCI.py:471). Needs fuel/oxidizer compositions
        defined first; zone compositions come from the stoichiometric
        balance at each phi."""
        if self._fuel_recipe is None or self._oxid_recipe is None:
            logger.error("define fuel and oxidizer compositions first")
            return
        if self._check_zonal(zonephi, "zonal equivalence ratio"):
            self.zonephi = [float(p) for p in zonephi]
            self._zonalsetupmode = 2

    def set_energy_equation_switch_ON_CA(self, switchCA: float):
        """Suppress chemistry until this CA (reference HCCI.py:559)."""
        if not self.IVCCA < switchCA < self.EVOCA:
            logger.error("switch CA must lie inside (IVC, EVO)")
            return
        self._energy_switch_CA = float(switchCA)

    # ------------------------------------------------------------------

    def _zone_initials(self):
        mech = self._effective_mech()
        KK = mech.n_species
        NZ = self._nzones
        T0 = self.reactor_condition.temperature
        zone_T = (np.asarray(self.zonetemperature)
                  if self.zonetemperature else np.full(NZ, T0))
        vol = (np.asarray(self.zonevolume)
               if self.zonevolume else np.full(NZ, 1.0 / NZ))
        if self._zonalsetupmode == 1 and self.zonemolefractions:
            from ..ops import thermo
            import jax.numpy as jnp
            zone_Y = np.stack([
                np.asarray(thermo.X_to_Y(
                    mech, jnp.asarray(x / np.sum(x))))
                for x in self.zonemolefractions])
        elif self._zonalsetupmode == 2 and self.zonephi:
            zone_Y = np.stack([
                self._mixture_from_phi(phi) for phi in self.zonephi])
        else:
            zone_Y = np.broadcast_to(np.asarray(self.reactor_condition.Y),
                                     (NZ, KK)).copy()
        return zone_T, vol, zone_Y

    def _recipe_to_x(self, recipe) -> np.ndarray:
        mech = self._effective_mech()
        x = np.zeros(mech.n_species)
        items = recipe.items() if isinstance(recipe, dict) else recipe
        for name, f in items:
            x[mech.species_index(name)] += float(f)
        return x

    def _mixture_from_phi(self, phi: float) -> np.ndarray:
        """Mass fractions for one zone at equivalence ratio phi using the
        fuel/oxidizer recipes (reference HCCI.py:728 keyword path)."""
        from ..mixture import Mixture as Mix

        if not self._product_names:
            raise ValueError("define_product_composition must list the "
                             "complete-combustion products first")
        mix = Mix(self.chemistry)
        mix.temperature = self.reactor_condition.temperature
        mix.pressure = self.reactor_condition.pressure
        fuel = self._recipe_to_x(self._fuel_recipe)
        oxid = self._recipe_to_x(self._oxid_recipe)
        mix.X_by_Equivalence_Ratio(self.chemistry, fuel, oxid,
                                   np.zeros_like(fuel),
                                   self._product_names, float(phi))
        return np.asarray(mix.Y)

    def run(self) -> int:
        """Integrate IVC -> EVO (reference HCCI.py:1241)."""
        import time as _time

        self.consume_protected_keywords()
        zone_T, vol, zone_Y = self._zone_initials()
        geo = self._geometry()
        ht = self._heat_transfer()
        rtol, atol = self.tolerances
        t0 = _time.perf_counter()
        sol = engine_ops.solve_hcci(
            self._effective_mech(), geo,
            T0=self.reactor_condition.temperature,
            P0=self.reactor_condition.pressure,
            Y0=np.asarray(self.reactor_condition.Y),
            start_CA=self.IVCCA, end_CA=self.EVOCA,
            ht=ht, zone_T=zone_T, zone_vol_frac=vol, zone_Y=zone_Y,
            zone_mass_frac=(np.asarray(self.zonemass)
                            if self.usezonemass else None),
            zone_ht_frac=(np.asarray(self.zoneHTarea)
                          if self.zoneHTarea else None),
            n_zones=self._nzones,
            energy_switch_CA=self._energy_switch_CA,
            rtol=max(rtol, 1e-9), atol=atol)
        self._engine_solution = sol
        ok = bool(sol.success)
        status = int(sol.status)
        self.runstatus = STATUS_SUCCESS if ok else STATUS_FAILED
        self._record_solve(
            wall_s=round(_time.perf_counter() - t0, 6), success=ok,
            status=status, status_name=status_name_of(status),
            n_steps=int(sol.n_steps), n_zones=self._nzones,
            start_CA=self.IVCCA, end_CA=self.EVOCA)
        return 0 if ok else 1

    def get_ignition_CA(self) -> float:
        """CA of peak mass-averaged dT/dt (nan if no ignition)."""
        if self._engine_solution is None:
            raise RuntimeError("please run the engine simulation first.")
        return float(self._engine_solution.ignition_CA)
