"""Reactor and flame model classes (the reference's L3/L4 layers,
SURVEY.md §1): the Keyword/Profile/ReactorModel framework plus the
concrete user-facing simulation classes."""

from .batch import (
    BatchReactors,
    GivenPressureBatchReactor_EnergyConservation,
    GivenPressureBatchReactor_FixedTemperature,
    GivenVolumeBatchReactor_EnergyConservation,
    GivenVolumeBatchReactor_FixedTemperature,
)
from .reactormodel import (
    BooleanKeyword,
    IntegerKeyword,
    Keyword,
    Profile,
    ReactorModel,
    RealKeyword,
    StringKeyword,
)

__all__ = [
    "BatchReactors",
    "BooleanKeyword",
    "GivenPressureBatchReactor_EnergyConservation",
    "GivenPressureBatchReactor_FixedTemperature",
    "GivenVolumeBatchReactor_EnergyConservation",
    "GivenVolumeBatchReactor_FixedTemperature",
    "IntegerKeyword",
    "Keyword",
    "Profile",
    "ReactorModel",
    "RealKeyword",
    "StringKeyword",
]
