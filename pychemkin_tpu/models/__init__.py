"""Reactor and flame model classes (the reference's L3/L4 layers,
SURVEY.md §1): the Keyword/Profile/ReactorModel framework plus the
concrete user-facing simulation classes."""

from .batch import (
    BatchReactors,
    GivenPressureBatchReactor_EnergyConservation,
    GivenPressureBatchReactor_FixedTemperature,
    GivenVolumeBatchReactor_EnergyConservation,
    GivenVolumeBatchReactor_FixedTemperature,
)
from .pfr import (
    PlugFlowReactor,
    PlugFlowReactor_EnergyConservation,
    PlugFlowReactor_FixedTemperature,
)
from .psr import (
    PSR_SetResTime_EnergyConservation,
    PSR_SetResTime_FixedTemperature,
    PSR_SetVolume_EnergyConservation,
    PSR_SetVolume_FixedTemperature,
    openreactor,
    perfectlystirredreactor,
)
from .reactormodel import (
    BooleanKeyword,
    IntegerKeyword,
    Keyword,
    Profile,
    ReactorModel,
    RealKeyword,
    StringKeyword,
)
from .steadystatesolver import SteadyStateSolver

__all__ = [
    "BatchReactors",
    "BooleanKeyword",
    "GivenPressureBatchReactor_EnergyConservation",
    "GivenPressureBatchReactor_FixedTemperature",
    "GivenVolumeBatchReactor_EnergyConservation",
    "GivenVolumeBatchReactor_FixedTemperature",
    "IntegerKeyword",
    "Keyword",
    "PSR_SetResTime_EnergyConservation",
    "PSR_SetResTime_FixedTemperature",
    "PSR_SetVolume_EnergyConservation",
    "PSR_SetVolume_FixedTemperature",
    "PlugFlowReactor",
    "PlugFlowReactor_EnergyConservation",
    "PlugFlowReactor_FixedTemperature",
    "Profile",
    "ReactorModel",
    "RealKeyword",
    "SteadyStateSolver",
    "StringKeyword",
    "openreactor",
    "perfectlystirredreactor",
]
