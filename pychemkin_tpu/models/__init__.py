"""Reactor and flame model classes (the reference's L3/L4 layers,
SURVEY.md §1): the Keyword/Profile/ReactorModel framework plus the
concrete user-facing simulation classes."""

from .engine import Engine
from .hcci import HCCIengine
from .si import SIengine
from .batch import (
    BatchReactors,
    GivenPressureBatchReactor_EnergyConservation,
    GivenPressureBatchReactor_FixedTemperature,
    GivenVolumeBatchReactor_EnergyConservation,
    GivenVolumeBatchReactor_FixedTemperature,
)
from .flame import Flame
from .grid import Grid
from .pfr import (
    PlugFlowReactor,
    PlugFlowReactor_EnergyConservation,
    PlugFlowReactor_FixedTemperature,
)
from .premixedflame import (
    BurnedStabilized_EnergyEquation,
    BurnedStabilized_GivenTemperature,
    FreelyPropagating,
    PremixedFlame,
)
from .psr import (
    PSR_SetResTime_EnergyConservation,
    PSR_SetResTime_FixedTemperature,
    PSR_SetVolume_EnergyConservation,
    PSR_SetVolume_FixedTemperature,
    openreactor,
    perfectlystirredreactor,
)
from .reactornetwork import ClusterNotApplicableError, ReactorNetwork
from .reactormodel import (
    BooleanKeyword,
    IntegerKeyword,
    Keyword,
    Profile,
    ReactorModel,
    RealKeyword,
    StringKeyword,
)
from .steadystatesolver import SteadyStateSolver

__all__ = [
    "BatchReactors",
    "BooleanKeyword",
    "BurnedStabilized_EnergyEquation",
    "BurnedStabilized_GivenTemperature",
    "Engine",
    "Flame",
    "FreelyPropagating",
    "Grid",
    "HCCIengine",
    "SIengine",
    "PremixedFlame",
    "ClusterNotApplicableError",
    "ReactorNetwork",
    "GivenPressureBatchReactor_EnergyConservation",
    "GivenPressureBatchReactor_FixedTemperature",
    "GivenVolumeBatchReactor_EnergyConservation",
    "GivenVolumeBatchReactor_FixedTemperature",
    "IntegerKeyword",
    "Keyword",
    "PSR_SetResTime_EnergyConservation",
    "PSR_SetResTime_FixedTemperature",
    "PSR_SetVolume_EnergyConservation",
    "PSR_SetVolume_FixedTemperature",
    "PlugFlowReactor",
    "PlugFlowReactor_EnergyConservation",
    "PlugFlowReactor_FixedTemperature",
    "Profile",
    "ReactorModel",
    "RealKeyword",
    "SteadyStateSolver",
    "StringKeyword",
    "openreactor",
    "perfectlystirredreactor",
]
