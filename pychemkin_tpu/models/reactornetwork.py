"""Hybrid (PSR/PFR) equivalent-reactor-network solver (reference
hybridreactornetwork.py:39).

The network is a directed graph of already-configured PSR/PFR reactors
with outflow-split edges, external outlets, and optional recycle loops.
Reactors are solved ONE AT A TIME in insertion order (Gauss-Seidel
sequential substitution); each reactor's internal inlet is synthesized by
adiabatic mixing of the upstream outlet splits
(hybridreactornetwork.py:706 calculate_incoming_streams). Networks with
recycle loops declare "tear points" and iterate the whole sequence to a
fixed point with under-relaxation (run_with_tearstream
:1069; relaxation :1382/:1425; convergence via compare_streams :1400;
defaults: 200 iterations :117, tol 1e-6 :119).

This layer is pure Python orchestration over the batched JAX reactor
kernels — exactly the reference's L5 position (SURVEY.md §1). The
per-iteration reactor solves are already jit-compiled and warm-started,
so the sequential loop's cost is the physics, not the plumbing.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .. import telemetry
from ..chemistry import Chemistry
from ..inlet import (
    Stream,
    adiabatic_mixing_streams,
    clone_stream,
    compare_streams,
)
from ..logger import logger
from .pfr import PlugFlowReactor
from .psr import perfectlystirredreactor as PSR

NetworkReactor = Union[PSR, PlugFlowReactor]

#: inlet-registry key used for the synthesized internal inlet
_INTERNAL_INLET = "from_network_internal"


class ClusterNotApplicableError(RuntimeError):
    """Raised by :meth:`ReactorNetwork.run_cluster` when the network is
    not the linear SetResTime/ENRG PSR chain the coupled cluster solve
    handles. ``rule`` names the topology rule that failed (the same
    machine-readable tag logged by the ``cluster_reject`` telemetry
    event); the message stays human-readable and points at ``run()``.
    """

    def __init__(self, rule: str, detail: str):
        self.rule = rule
        self.detail = detail
        super().__init__(
            "run_cluster needs a linear chain of "
            "PSR_SetResTime_EnergyConservation reactors; use run() for "
            f"general networks [{rule}: {detail}]")


class ReactorNetwork:
    """Hybrid reactor network with outflow splitting and optional tear
    streams (reference hybridreactornetwork.py:39)."""

    _exit_index = -10
    _exit_name = "EXIT>>"

    def __init__(self, chem: Chemistry):
        if not isinstance(chem, Chemistry):
            raise TypeError('the parameter must be a "Chemistry Set" '
                            "object")
        self.network_chem = chem
        self.numb_reactors = 0
        self.last_reactor = 0
        self.numb_external_outlet = 0
        self.external_outlets: Dict[int, int] = {}
        self.external_outlet_streams: Dict[int, Stream] = {}
        self.reactor_map: Dict[str, int] = {}
        self.reactor_objects: Dict[int, NetworkReactor] = {}
        self.reactor_solutions: Dict[int, Stream] = {}
        self.outflow_targets: Dict[int, List[Tuple[int, float]]] = {}
        self.outflow_altered = True
        self.external_connections: Dict[int, int] = {}
        self.inflow_sources: Dict[int, List[Tuple[int, float]]] = {}
        self.internal_inflow: Dict[int, Stream] = {}
        self.internal_inflow_ready: Dict[int, bool] = {}
        self.numb_tearpoints = 0
        self.tearpoint: List[int] = []
        self.max_tearloop_count = 200          # reference :117
        self.tolerance = 1.0e-6                # reference :119
        self.relaxation = 1.0                  # 1.0 = no relaxation
        self.tear_converged = False
        self._run_status = -100
        #: (rule, detail) of the last cluster-mode rejection, or None
        self._cluster_reject_reason: Optional[Tuple[str, str]] = None

    # --- membership (reference :127-341) --------------------------------

    def get_reactor_label(self, reactor_index: int) -> str:
        """(reference :127)."""
        for name, idx in self.reactor_map.items():
            if idx == reactor_index:
                return name
        return f"<reactor {reactor_index}>"

    def add_reactor(self, reactor: NetworkReactor):
        """Register a configured PSR/PFR; insertion order = solve order
        (reference :160)."""
        if not isinstance(reactor, (PSR, PlugFlowReactor)):
            raise TypeError("network reactors must be PSR or PFR models")
        label = reactor.label or f"reactor{self.numb_reactors + 1}"
        if label in self.reactor_map:
            raise ValueError(f"reactor label {label!r} already in the "
                             "network")
        if reactor.chemID != self.network_chem.chemID:
            raise ValueError("all network reactors must share the "
                             "network chemistry set")
        self.numb_reactors += 1
        idx = self.numb_reactors
        self.last_reactor = idx
        self.reactor_map[label] = idx
        self.reactor_objects[idx] = reactor
        self.internal_inflow_ready[idx] = False
        # count the reactor's externally-attached inlets (PSR registry /
        # the PFR's constructor stream)
        if isinstance(reactor, PSR):
            self.external_connections[idx] = reactor.numbinlets
        else:
            self.external_connections[idx] = 1
        self.outflow_altered = True

    def add_reactor_list(self, reactor_list: List[NetworkReactor]):
        """(reference :223)."""
        for r in reactor_list:
            self.add_reactor(r)

    def show_reactors(self):
        """(reference :239)."""
        for name, idx in self.reactor_map.items():
            kind = type(self.reactor_objects[idx]).__name__
            print(f"  [{idx}] {name} ({kind})")

    @property
    def number_reactors(self) -> int:
        """(reference :256)."""
        return self.numb_reactors

    @property
    def number_external_outlets(self) -> int:
        """(reference :268)."""
        return self.numb_external_outlet

    # --- connectivity (reference :343-705) ------------------------------

    def add_outflow_connections(self, source_label: str,
                                outflow_split: List[Tuple[str, float]]):
        """Outflow splits from ``source_label``: list of (target name or
        ``"EXIT>>"``, fraction). An unlisted remainder goes to the
        immediate downstream reactor (through flow)
        (reference :343)."""
        if source_label not in self.reactor_map:
            raise ValueError(f"reactor {source_label!r} is NOT in the "
                             "network.")
        reactor_index = self.reactor_map[source_label]
        downstream = reactor_index + 1
        connect_table: List[Tuple[int, float]] = []
        total_frac = 0.0
        thruflow = False
        for name, frac in outflow_split:
            if not 0.0 <= frac <= 1.0:
                raise ValueError(f"outflow split fraction to {name!r} "
                                 "must be within [0, 1]")
            if name == self._exit_name:
                self.set_external_outlet(reactor_index)
                target = self._exit_index
            else:
                if name not in self.reactor_map:
                    raise ValueError(f"target reactor {name!r} is NOT "
                                     "in the network.")
                target = self.reactor_map[name]
                if target == reactor_index:
                    raise ValueError("outflow connection to self "
                                     f"{source_label!r} is not allowed.")
                if target == downstream:
                    thruflow = True
            connect_table.append((target, frac))
            total_frac += frac
        if total_frac > 1.0 + 1e-9:
            raise ValueError("outflow split fractions sum to "
                             f"{total_frac:.6f} > 1")
        remainder = 1.0 - total_frac
        if remainder > 1e-9 and not thruflow:
            if downstream <= self.numb_reactors:
                connect_table.append((downstream, remainder))
            else:
                # last reactor: the remainder leaves the network
                self.set_external_outlet(reactor_index)
                connect_table.append((self._exit_index, remainder))
        self.outflow_targets[reactor_index] = connect_table
        self.outflow_altered = True

    def clear_connections(self):
        """(reference :511)."""
        self.outflow_targets.clear()
        self.inflow_sources.clear()
        self.internal_inflow.clear()
        for idx in self.internal_inflow_ready:
            self.internal_inflow_ready[idx] = False
        self.outflow_altered = True

    def remove_reactor(self, name: str):
        """(reference :525). Drops the reactor, every connection that
        references it, and REINDEXES the remaining reactors compactly in
        their original order — index gaps would break the implicit
        through-flow convention (downstream = idx + 1) and the
        last-reactor external-outlet defaulting."""
        if name not in self.reactor_map:
            raise KeyError(f"no reactor named {name!r}")
        removed = self.reactor_map.pop(name)
        old_order = sorted(self.reactor_objects)
        remap = {}
        new_i = 0
        for old_i in old_order:
            if old_i == removed:
                continue
            new_i += 1
            remap[old_i] = new_i

        def _r(i):
            return remap.get(i, i if i == self._exit_index else None)

        self.reactor_objects = {
            remap[i]: r for i, r in self.reactor_objects.items()
            if i != removed}
        self.reactor_map = {n: remap[i]
                            for n, i in self.reactor_map.items()}
        self.outflow_targets = {
            remap[srci]: [(_r(t), f) for t, f in table
                          if t == self._exit_index or
                          (t != removed and t in remap)]
            for srci, table in self.outflow_targets.items()
            if srci != removed}
        self.external_outlets = {
            k: remap[v] for k, v in self.external_outlets.items()
            if v != removed}
        self.numb_external_outlet = len(self.external_outlets)
        self.external_connections = {
            remap[i]: n for i, n in self.external_connections.items()
            if i != removed}
        self.internal_inflow_ready = {
            remap[i]: v for i, v in self.internal_inflow_ready.items()
            if i != removed}
        self.internal_inflow = {}
        self.reactor_solutions = {}
        self.tearpoint = [remap[i] for i in self.tearpoint
                          if i != removed]
        self.numb_tearpoints = len(self.tearpoint)
        self.numb_reactors -= 1
        self.last_reactor = self.numb_reactors
        self.outflow_altered = True

    def set_reactor_outflow(self):
        """Build the inflow graph from the outflow tables
        (reference :604). Reactors without an explicit outflow table get
        a pure through-flow edge (or an external outlet for the last)."""
        for idx in self.reactor_objects:
            if idx not in self.outflow_targets:
                if idx < self.numb_reactors:
                    self.outflow_targets[idx] = [(idx + 1, 1.0)]
                else:
                    self.set_external_outlet(idx)
                    self.outflow_targets[idx] = [(self._exit_index, 1.0)]
        self.set_inflow_connections()
        self.outflow_altered = False

    def set_inflow_connections(self):
        """Invert outflow_targets into inflow_sources
        (reference :671)."""
        self.inflow_sources = {}
        for src, table in self.outflow_targets.items():
            for target, frac in table:
                if target == self._exit_index or frac <= 0.0:
                    continue
                self.inflow_sources.setdefault(target, []).append(
                    (src, frac))

    def set_external_outlet(self, reactor_index: int):
        """(reference :692)."""
        if reactor_index not in self.external_outlets.values():
            self.numb_external_outlet += 1
            self.external_outlets[self.numb_external_outlet] = \
                reactor_index

    def show_internal_outflow_connections(self):
        """(reference :279)."""
        for src, table in self.outflow_targets.items():
            for target, frac in table:
                t = (self._exit_name if target == self._exit_index
                     else self.get_reactor_label(target))
                print(f"  {self.get_reactor_label(src)} --{frac:.3f}--> "
                      f"{t}")

    def show_internal_inflow_connections(self):
        """(reference :315)."""
        for target, table in self.inflow_sources.items():
            for src, frac in table:
                print(f"  {self.get_reactor_label(target)} <--{frac:.3f}"
                      f"-- {self.get_reactor_label(src)}")

    # --- internal-inlet synthesis (reference :706-845) ------------------

    def calculate_incoming_streams(self,
                                   reactor_index: int) -> Optional[Stream]:
        """Mass-flow-weighted adiabatic merge of every solved upstream
        split into one inlet stream (reference :706)."""
        sources = self.inflow_sources.get(reactor_index)
        if not sources:
            return None
        incoming: Optional[Stream] = None
        for src, frac in sources:
            sol = self.reactor_solutions.get(src)
            if sol is None:
                # source not solved yet (first pass of a recycle loop)
                continue
            piece = Stream(self.network_chem,
                           label="from_network_internal")
            clone_stream(sol, piece)
            piece.mass_flowrate = sol.mass_flowrate * frac
            if incoming is None:
                incoming = piece
            else:
                merged = adiabatic_mixing_streams(piece, incoming)
                clone_stream(merged, incoming)
                incoming.mass_flowrate = merged.mass_flowrate
        return incoming

    def set_internal_inlet(self, reactor_index: int) -> int:
        """(reference :783)."""
        inlet_stream = self.calculate_incoming_streams(reactor_index)
        if inlet_stream is None:
            if reactor_index not in self.external_connections or \
                    self.external_connections[reactor_index] == 0:
                raise RuntimeError(
                    f"run failure: reactor "
                    f"{self.get_reactor_label(reactor_index)} is not "
                    "connected to other reactors")
            return 1
        self.internal_inflow[reactor_index] = copy.deepcopy(inlet_stream)
        return 0

    def create_internal_inlet(self, reactor_index: int):
        """Attach/update the merged internal inlet on the reactor
        (reference :827)."""
        status = self.set_internal_inlet(reactor_index)
        if status != 0:
            return
        rxtor = self.reactor_objects[reactor_index]
        stream = self.internal_inflow[reactor_index]
        if isinstance(rxtor, PSR):
            if self.internal_inflow_ready[reactor_index]:
                rxtor.reset_inlet(stream, _INTERNAL_INLET)
            else:
                rxtor.set_inlet(stream, _INTERNAL_INLET)
                self.internal_inflow_ready[reactor_index] = True
        else:
            # a PFR's inlet IS its feed stream: replace the state the
            # marcher starts from
            rxtor.set_inlet_stream(stream)
            self.internal_inflow_ready[reactor_index] = True

    # --- run (reference :869-1243) --------------------------------------

    def get_network_run_status(self) -> int:
        """(reference :847)."""
        return self._run_status

    def run(self) -> int:
        """Solve the network (reference :869): sequential substitution,
        with tear-stream fixed-point iteration when tear points are
        declared."""
        if self.numb_reactors == 0:
            raise RuntimeError("the network has no reactors")
        if self.outflow_altered:
            self.set_reactor_outflow()
        for idx, rxtor in self.reactor_objects.items():
            has_external = (rxtor.numbinlets > 0
                            if isinstance(rxtor, PSR) else True)
            if not has_external and idx not in self.inflow_sources:
                raise RuntimeError(
                    f"run failure: reactor {self.get_reactor_label(idx)}"
                    " is not connected to other reactors")
        if self.numb_tearpoints == 0:
            status = self.run_without_tearstream()
        else:
            status = self.run_with_tearstream()
        self._run_status = status
        return status

    # --- PSR cluster mode (reference PSR.py:286/:464) -------------------
    def _reject_cluster(self, rule: str, detail: str) -> None:
        """Record WHY cluster mode is not applicable (VERDICT Missing
        #3: the rejection branches used to return None silently): a
        structured ``cluster_reject`` telemetry event + log line, and
        the reason stored in ``_cluster_reject_reason`` for
        :meth:`run_cluster` to raise with."""
        self._cluster_reject_reason = (rule, detail)
        logger.info("cluster mode not applicable — %s: %s", rule, detail)
        rec = telemetry.get_recorder()
        rec.event("cluster_reject", rule=rule, detail=detail)
        rec.inc("network.cluster_reject")
        return None

    def _linear_psr_chain(self) -> Optional[List[int]]:
        """The reactor indices as a linear PSR chain (each reactor's
        whole outflow feeds the next; only the first has external
        inlets), or None when the topology/types don't qualify — the
        failed rule is logged and kept in ``self._cluster_reject_reason``."""
        idxs = sorted(self.reactor_objects)
        from .psr import PSR_SetResTime_EnergyConservation

        self._cluster_reject_reason = None
        for pos, idx in enumerate(idxs):
            r = self.reactor_objects[idx]
            label = self.get_reactor_label(idx)
            if not isinstance(r, PSR_SetResTime_EnergyConservation):
                return self._reject_cluster(
                    "reactor_type",
                    f"reactor {label!r} is {type(r).__name__}, not "
                    "PSR_SetResTime_EnergyConservation")
            targets = self.outflow_targets.get(idx, [])
            if pos < len(idxs) - 1:
                if len(targets) != 1 or targets[0][0] != idxs[pos + 1] \
                        or abs(targets[0][1] - 1.0) > 1e-12:
                    return self._reject_cluster(
                        "midchain_outflow",
                        f"reactor {label!r} must send its WHOLE outflow "
                        "to the next reactor in insertion order; found "
                        f"{len(targets)} split(s)")
            else:
                # the LAST reactor must flow only to the exit — a
                # recycle split back into the chain is NOT a linear
                # chain and needs run()'s tear-stream machinery
                if len(targets) != 1 \
                        or targets[0][0] != self._exit_index:
                    return self._reject_cluster(
                        "tail_outflow",
                        f"last reactor {label!r} must flow only to "
                        f"{self._exit_name} (recycle splits need run()'s "
                        "tear streams)")
            if pos > 0 and r.numbinlets > 0:
                return self._reject_cluster(
                    "downstream_inlet",
                    f"reactor {label!r} has {r.numbinlets} external "
                    "inlet(s); only the chain head may be externally fed")
        if not idxs or self.reactor_objects[idxs[0]].numbinlets == 0:
            return self._reject_cluster(
                "head_inlet",
                "the chain head has no external inlet"
                if idxs else "the network has no reactors")
        return idxs

    def _cluster_inputs(self):
        """Validate the network as a linear PSR chain and assemble the
        coupled-solve inputs (shared by :meth:`run_cluster` and
        :meth:`run_cluster_scan`). Raises
        :class:`ClusterNotApplicableError` naming the failed rule.
        Returns ``(chain, head, mech, Y_in0, h_in0, mdot, taus, qloss,
        T_g, Y_g)``."""
        if self.outflow_altered:
            self.set_reactor_outflow()
        chain = self._linear_psr_chain()
        if chain is None:
            rule, detail = (self._cluster_reject_reason
                            or ("unknown", "topology not a linear chain"))
            raise ClusterNotApplicableError(rule, detail)
        head = self.reactor_objects[chain[0]]
        for i in chain[1:]:
            if abs(self.reactor_objects[i].pressure
                   - head.pressure) > 1e-9 * head.pressure:
                self._reject_cluster(
                    "pressure_mismatch",
                    "run_cluster solves the chain at one pressure; "
                    f"reactor {self.get_reactor_label(i)!r} differs "
                    "from the head")
                raise ClusterNotApplicableError(
                    *self._cluster_reject_reason)
        Y_in0, h_in0, mdot = head.combined_inlet()
        taus = [self.reactor_objects[i].residence_time for i in chain]
        qloss = [self.reactor_objects[i].heat_loss_rate for i in chain]
        T_g, Y_g = [], []
        for pos, i in enumerate(chain):
            r = self.reactor_objects[i]
            if r._estimate_T is None:
                r.set_estimate_conditions()    # equilibrium estimate
            if r._estimate_T is None and pos > 0:
                # downstream reactors have no external inlet to
                # equilibrate from; their construction mixture can sit
                # far enough off the ignited branch that the coupled
                # damped Newton rides its per-iteration trust caps into
                # the wrong basin. Warm-start from the HEAD's
                # equilibrium estimate — every reactor of an ignited
                # chain lies near that state. An explicitly-set user
                # composition estimate is kept.
                r.reset_estimate_temperature(T_g[0])
                if r._estimate_Y is None:
                    r._estimate_Y = np.asarray(Y_g[0])
            tg, yg = r._guess()
            T_g.append(tg)
            Y_g.append(yg)
        mech = head._effective_mech()
        return (chain, head, mech, Y_in0, h_in0, mdot, taus, qloss,
                T_g, Y_g)

    def run_cluster(self) -> int:
        """Solve a linear PSR chain as ONE coupled Newton system — the
        TPU-native form of the reference's cluster mode, where
        clustered PSRs solve in a single native call (reference
        PSR.py:286 set_reactor_index, :464 cluster_process_keywords;
        exercised by its PSRChain_network example) instead of the
        sequential substitution of :meth:`run`. The caller explicitly
        asked for cluster mode, so an inapplicable topology raises a
        typed :class:`ClusterNotApplicableError` naming the rule that
        failed (the same reason logged by the ``cluster_reject``
        telemetry event)."""
        import jax.numpy as jnp

        from ..ops import psr as psr_ops_mod

        (chain, head, mech, Y_in0, h_in0, mdot, taus, qloss,
         T_g, Y_g) = self._cluster_inputs()
        sol = psr_ops_mod.solve_psr_chain(
            mech, "ENRG", P=head.pressure, Y_in0=Y_in0, h_in0=h_in0,
            taus=taus, T_guess=np.asarray(T_g), Y_guess=np.asarray(Y_g),
            qloss=np.asarray(qloss), mdot=mdot)
        if not bool(sol.converged):
            logger.error("PSR cluster solve did not converge "
                         "(residual %.2e)", float(sol.residual))
            self._run_status = 1
            return 1
        # store per-reactor solutions exactly like the sequential path;
        # downstream reactors also get their internal inlet registered
        # (flow bookkeeping for process_solution / exit streams)
        for pos, idx in enumerate(chain):
            r = self.reactor_objects[idx]
            vol = float(taus[pos]) * mdot / float(sol.rho[pos])
            r._solution = psr_ops_mod.PSRSolution(
                T=jnp.asarray(sol.T[pos]), Y=jnp.asarray(sol.Y[pos]),
                rho=jnp.asarray(sol.rho[pos]),
                tau=jnp.asarray(taus[pos]),
                volume=jnp.asarray(vol),
                residual=sol.residual, converged=sol.converged,
                n_newton=sol.n_newton)
            r.runstatus = 0
            r._estimate_T = float(sol.T[pos])
            r._estimate_Y = np.asarray(sol.Y[pos])
            if pos > 0:
                self.create_internal_inlet(idx)
            self.reactor_solutions[idx] = r.process_solution()
        self.set_external_streams()
        self._run_status = 0
        return 0

    def run_cluster_scan(self, tau_scales, *, chunk_size=None,
                         checkpoint_path=None, job_report=None,
                         driver_kwargs=None):
        """Cluster S-curve scan: the linear PSR chain re-solved at
        scaled residence times — scan point ``s`` solves the chain with
        every reactor's ``tau`` multiplied by ``tau_scales[s]`` (the
        blow-off/extinction scan the reference walks serially, one
        continuation step per native call). The whole scan is ONE
        vmapped coupled solve per chunk, driven as a durable job:
        ``chunk_size`` / ``checkpoint_path`` / ``job_report`` /
        ``driver_kwargs`` behave exactly as in
        :meth:`pychemkin_tpu.models.psr.perfectlystirredreactor.run_sweep`.

        Validates the topology like :meth:`run_cluster` (raises
        :class:`ClusterNotApplicableError` when not a linear chain).
        Returns ``(T [S, n_chain], Y [S, n_chain, KK], converged [S],
        status [S])``; the network's stored per-reactor solutions are
        NOT touched (this is a scan, not a run)."""
        import jax
        import jax.numpy as jnp

        from ..ops import psr as psr_ops_mod
        from ..resilience import checkpoint as _checkpoint
        from ..resilience import driver as _driver

        (chain, head, mech, Y_in0, h_in0, mdot, taus, qloss,
         T_g, Y_g) = self._cluster_inputs()
        scales = jnp.atleast_1d(jnp.asarray(tau_scales, jnp.float64))
        S = int(scales.shape[0])
        taus_j = jnp.asarray(taus, jnp.float64)
        qloss_j = jnp.asarray(qloss, jnp.float64)
        T_gj, Y_gj = jnp.asarray(T_g), jnp.asarray(Y_g)
        Y_in0j = jnp.asarray(Y_in0)

        def one(scale):
            return psr_ops_mod.solve_psr_chain(
                mech, "ENRG", P=head.pressure, Y_in0=Y_in0j,
                h_in0=h_in0, taus=taus_j * scale, T_guess=T_gj,
                Y_guess=Y_gj, qloss=qloss_j, mdot=mdot)

        vm = jax.vmap(one)

        sig = None
        if checkpoint_path is not None:
            sig = _checkpoint.signature(
                "network.run_cluster_scan", head.pressure, h_in0, mdot,
                arrays=(scales, taus_j, qloss_j, T_gj, Y_gj, Y_in0j),
                tree=mech)

        def index_solve(idx):
            sol = vm(scales[idx])
            return {"T": sol.T, "Y": sol.Y,
                    "converged": sol.converged, "status": sol.status}

        results, _report = _driver.run_vmapped_sweep_job(
            index_solve, S, chunk_size=chunk_size,
            checkpoint_path=checkpoint_path, signature=sig,
            result_keys=("T", "Y", "converged", "status"),
            job_report=job_report, label="network.run_cluster_scan",
            **(driver_kwargs or {}))
        return (results["T"], results["Y"], results["converged"],
                results["status"])

    def _run_one(self, idx: int) -> Stream:
        rxtor = self.reactor_objects[idx]
        if isinstance(rxtor, PSR) and not rxtor.checkrunstatus():
            # first solve of this node: estimate from the equilibrium of
            # its combined inlet — the reference warm-starts from the
            # incoming composition (hybridreactornetwork.py:1039), but
            # the ignited-branch Newton is far more robust from the
            # equilibrium state; on later tear iterations the reactor's
            # own previous solution is the estimate (PSR.run stores it)
            rxtor.set_estimate_conditions()
        rc = rxtor.run()
        if rc != 0:
            raise RuntimeError(
                f"run failure: reactor {self.get_reactor_label(idx)} "
                f"error code = {rc}")
        if isinstance(rxtor, PSR):
            return rxtor.process_solution()
        rxtor.process_solution()
        return rxtor.get_exit_stream()

    def run_without_tearstream(self) -> int:
        """(reference :1018)."""
        for idx in sorted(self.reactor_objects):
            if idx in self.inflow_sources:
                self.create_internal_inlet(idx)
            self.reactor_solutions[idx] = self._run_one(idx)
        self.set_external_streams()
        return 0

    def run_with_tearstream(self) -> int:
        """(reference :1069)."""
        self.tear_converged = False
        last_solutions: Dict[int, Stream] = {}
        loop_count = 0
        loop_residual = np.inf
        while not self.tear_converged and \
                loop_count < self.max_tearloop_count:
            logger.info("<---- running tear loop # %d ---->", loop_count)
            for idx in sorted(self.reactor_objects):
                if idx in self.inflow_sources:
                    self.create_internal_inlet(idx)
                self.reactor_solutions[idx] = self._run_one(idx)

            loop_residual = 0.0
            any_checked = False
            for idx in sorted(self.reactor_objects):
                stream_new = self.reactor_solutions[idx]
                stream_old = last_solutions.get(idx)
                if stream_old is None:
                    last_solutions[idx] = copy.deepcopy(stream_new)
                    continue
                if idx in self.tearpoint:
                    any_checked = True
                    _, residual = self.check_tearstream_convergence(
                        stream_old, stream_new)
                    loop_residual = max(loop_residual, residual)
                    flow_old = max(stream_old.mass_flowrate, 1e-300)
                    flow_residual = abs(stream_new.mass_flowrate
                                        - stream_old.mass_flowrate) \
                        / flow_old
                    loop_residual = max(loop_residual, flow_residual)
                updated = self.update_tear_solution(stream_new,
                                                    stream_old)
                clone_stream(updated, self.reactor_solutions[idx])
                self.reactor_solutions[idx].mass_flowrate = \
                    updated.mass_flowrate
                clone_stream(updated, last_solutions[idx])
                last_solutions[idx].mass_flowrate = \
                    updated.mass_flowrate
            if any_checked and loop_residual <= self.tolerance:
                self.tear_converged = True
            logger.info(">---- loop %d: max residual = %g ----<",
                        loop_count, loop_residual)
            loop_count += 1

        if not self.tear_converged:
            logger.error("failure to solve the reactor network: max "
                         "tear iteration count reached %d, residual %g",
                         self.max_tearloop_count, loop_residual)
            return 10
        logger.info("the reactor network is converged in %d iterations",
                    loop_count)
        self.set_external_streams()
        return 0

    # --- external outlets (reference :937-1016) -------------------------

    def set_external_streams(self):
        """Build the external outlet streams with their split flow
        (reference :937)."""
        self.external_outlet_streams = {}
        for out_idx, rx_idx in self.external_outlets.items():
            sol = self.reactor_solutions.get(rx_idx)
            if sol is None:
                continue
            frac = 0.0
            for target, f in self.outflow_targets.get(rx_idx, []):
                if target == self._exit_index:
                    frac += f
            out = Stream(self.network_chem,
                         label=f"{self.get_reactor_label(rx_idx)}.exit")
            clone_stream(sol, out)
            out.mass_flowrate = sol.mass_flowrate * frac
            self.external_outlet_streams[out_idx] = out

    def get_reactor_stream(self, reactor_name: str) -> Stream:
        """Solved outflow stream of one reactor (reference :893)."""
        if reactor_name not in self.reactor_map:
            raise KeyError(f"no reactor named {reactor_name!r}")
        idx = self.reactor_map[reactor_name]
        sol = self.reactor_solutions.get(idx)
        if sol is None:
            raise RuntimeError("run the network first")
        return sol

    def get_external_stream(self, stream_index: int) -> Stream:
        """(reference :982)."""
        if stream_index not in self.external_outlet_streams:
            raise KeyError(f"no external outlet {stream_index}")
        return self.external_outlet_streams[stream_index]

    # --- tear-stream utilities (reference :1246-1463) -------------------


    def check_iteration_count(self, count: int) -> bool:
        """True while the tear-loop count is under the limit
        (reference hybridreactornetwork.py:1362)."""
        return count < self.max_tearloop_count

    def add_tearingpoint(self, reactor_name: str):
        """(reference :1277)."""
        if reactor_name not in self.reactor_map:
            raise KeyError(f"no reactor named {reactor_name!r}")
        idx = self.reactor_map[reactor_name]
        if idx not in self.tearpoint:
            self.tearpoint.append(idx)
            self.numb_tearpoints = len(self.tearpoint)

    def remove_tearpoint(self, reactor_name: str):
        """(reference :1246)."""
        if reactor_name not in self.reactor_map:
            raise KeyError(f"no reactor named {reactor_name!r}")
        idx = self.reactor_map[reactor_name]
        if idx in self.tearpoint:
            self.tearpoint.remove(idx)
            self.numb_tearpoints = len(self.tearpoint)

    def set_tear_tolerance(self, tol: float = 1.0e-6):
        """(reference :1328)."""
        if tol <= 0.0:
            raise ValueError("tolerance must be positive")
        self.tolerance = float(tol)

    def set_tear_iteration_limit(self, max_count: int):
        """(reference :1345)."""
        if max_count <= 0:
            raise ValueError("iteration limit must be positive")
        self.max_tearloop_count = int(max_count)

    def set_relaxation_factor(self, relax: float):
        """Under-relaxation for the tear update: 0 < relax <= 1
        (reference :1382)."""
        if not 0.0 < relax <= 1.0:
            raise ValueError("relaxation factor must be in (0, 1]")
        self.relaxation = float(relax)

    def check_tearstream_convergence(self, streamA: Stream,
                                     streamB: Stream):
        """Max state/composition residual between two iterates
        (reference :1400; uses compare_streams semantics)."""
        T_res = abs(streamB.temperature - streamA.temperature) \
            / max(abs(streamA.temperature), 1e-300)
        Y_res = float(np.max(np.abs(np.asarray(streamB.Y)
                                    - np.asarray(streamA.Y))))
        residual = max(T_res, Y_res)
        same, _, _ = compare_streams(streamA, streamB,
                                     atol=self.tolerance,
                                     rtol=self.tolerance)
        return same, residual

    def update_tear_solution(self, new_stream: Stream,
                             old_stream: Stream) -> Stream:
        """Relaxed iterate: relax*new + (1-relax)*old
        (reference :1425)."""
        lam = self.relaxation
        out = Stream(self.network_chem, label=new_stream.label)
        clone_stream(new_stream, out)
        out.temperature = (lam * new_stream.temperature
                           + (1 - lam) * old_stream.temperature)
        Y = (lam * np.asarray(new_stream.Y)
             + (1 - lam) * np.asarray(old_stream.Y))
        out.Y = np.clip(Y, 0.0, None)
        out.mass_flowrate = (lam * new_stream.mass_flowrate
                             + (1 - lam) * old_stream.mass_flowrate)
        return out
