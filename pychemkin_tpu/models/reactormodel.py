"""Reactor-model framework: Keyword / Profile / ReactorModel base classes.

TPU-native re-implementation of the reference's configuration backbone
(reference: src/ansys/chemkin/reactormodel.py). The reference assembles
keyword text lines and marshals them into the native solver
(``KINAll0D_SetUserKeyword``, reactormodel.py:966-1292); here keywords are
a typed, introspectable dict that the reactor models read directly when
they build the (pure, jittable) solve calls in
:mod:`pychemkin_tpu.ops`. The keyword names, defaults, and the
keyword-line rendering are preserved so decks written for the reference
read the same.

Run-status convention preserved (reference: reactormodel.py:769-773):
-100 = not yet run, 0 = success, other = failed — but a failed batched
solve reports per-element status instead of aborting (SURVEY.md §5).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .. import telemetry
from ..logger import logger
from ..mixture import Mixture

KeywordValue = Union[bool, int, float, str]

#: run-status codes (reference: reactormodel.py:769-773)
STATUS_NOT_RUN = -100
STATUS_SUCCESS = 0
STATUS_FAILED = 1


class Keyword:
    """One typed solver keyword (reference: reactormodel.py:50-377).

    ``protected`` keywords are managed by property setters / dedicated
    methods and rejected by the generic ``setkeyword`` in API mode
    (reference: reactormodel.py:60-93)."""

    #: keywords only settable through dedicated APIs
    PROTECTED = (
        "TIME", "PRES", "TEMP", "VOL", "QLOS", "HTC", "TAMB", "AREAQ",
        "TAU", "FLRT", "XEND",
    )
    #: profile-carrying keywords (reference: reactormodel.py:94-110)
    PROFILE_KEYS = ("TPRO", "PPRO", "VPRO", "QPRO", "AINT", "AREA", "DPRO",
                    "GRID", "MBPRO")

    #: API-call mode (True) vs full-keyword mode (False): under the
    #: full-keyword mode the entire input deck — protected keywords
    #: included — is supplied as keyword lines (reference:
    #: reactormodel.py:116; required there for multi-zone HCCI,
    #: HCCI.py:95-96). Class-level, like the reference.
    noFullKeyword = True

    @staticmethod
    def setfullkeywords(mode: bool):
        """Turn the full-keyword input mode ON/OFF
        (reference: reactormodel.py:183)."""
        Keyword.noFullKeyword = not mode

    def __init__(self, phrase: str, value: KeywordValue,
                 protected: bool = False):
        self._phrase = str(phrase).upper()
        self._value = value
        self._type = type(value)
        self._protected = protected
        self._prefix = ""           # '!' disables (reference :313-347)

    def resetvalue(self, value: KeywordValue):
        """(reference: reactormodel.py:258)."""
        if not isinstance(value, self._type) and not (
                self._type is float and isinstance(value, int)):
            raise TypeError(
                f"keyword {self._phrase} expects {self._type.__name__}")
        self._value = self._type(value)

    @property
    def parametertype(self) -> type:
        return self._type

    @property
    def value(self) -> KeywordValue:
        return self._value

    @property
    def keyphrase(self) -> str:
        return self._phrase

    @property
    def protected(self) -> bool:
        return self._protected

    def getvalue_as_string(self) -> Tuple[int, str]:
        """Render the keyword input line (reference:
        reactormodel.py:349-377). Booleans render as the bare keyword
        (present = on); other types as 'KEY value'. A '!'-disabled
        keyword (see :meth:`keyprefix`) renders commented out."""
        if self._type is bool:
            line = self._phrase if self._value else ""
            err = 0 if self._value else 1
        else:
            err, line = 0, f"{self._phrase} {self._value}"
        if line and self._prefix:
            line = self._prefix + line
        return err, line

    @property
    def keyprefix(self) -> bool:
        """True when the keyword is active, False when disabled by the
        '!' comment prefix (reference: reactormodel.py:335)."""
        return self._prefix != "!"

    @keyprefix.setter
    def keyprefix(self, on: bool):
        """Enable/disable the keyword by toggling the '!' prefix
        (reference: reactormodel.py:313)."""
        self._prefix = "" if on else "!"

    def show(self):
        print(self.getvalue_as_string()[1])


class BooleanKeyword(Keyword):
    """(reference: reactormodel.py:378)."""

    def __init__(self, phrase: str, value: bool = True):
        super().__init__(phrase, bool(value))


class IntegerKeyword(Keyword):
    """(reference: reactormodel.py:399)."""

    def __init__(self, phrase: str, value: int = 0):
        super().__init__(phrase, int(value))


class RealKeyword(Keyword):
    """(reference: reactormodel.py:421)."""

    def __init__(self, phrase: str, value: float = 0.0):
        super().__init__(phrase, float(value))


class StringKeyword(Keyword):
    """(reference: reactormodel.py:443)."""

    def __init__(self, phrase: str, value: str = ""):
        super().__init__(phrase, str(value))


class Profile:
    """Piecewise-linear (x, y) profile keyword
    (reference: reactormodel.py:467-671)."""

    def __init__(self, key: str, x, y):
        x = np.asarray(x, dtype=np.double)
        y = np.asarray(y, dtype=np.double)
        if x.ndim != 1 or x.shape != y.shape:
            raise ValueError("profile x and y must be equal-length 1-D")
        if len(x) < 2:
            raise ValueError("profile needs at least two points")
        if np.any(np.diff(x) <= 0.0):
            raise ValueError("profile x values must be strictly increasing")
        self._key = str(key).upper()
        self._x = x
        self._y = y

    @property
    def size(self) -> int:
        return len(self._x)

    @property
    def pos(self) -> np.ndarray:
        return self._x

    @property
    def value(self) -> np.ndarray:
        return self._y

    @property
    def profilekey(self) -> str:
        return self._key

    def resetprofile(self, x, y):
        """(reference: reactormodel.py:602)."""
        self.__init__(self._key, x, y)

    def getprofile_as_string_list(self) -> Tuple[int, List[str]]:
        """Render as 'KEY x y' input lines (reference:
        reactormodel.py:632)."""
        return 0, [f"{self._key} {x} {y}" for x, y in zip(self._x, self._y)]

    def show(self):
        for line in self.getprofile_as_string_list()[1]:
            print(line)


class ReactorModel:
    """Base class of every reactor model (reference:
    reactormodel.py:672).

    Holds a deep copy of the reactor-condition mixture/stream (the
    reference deep-copies too, reactormodel.py:690), the keyword and
    profile dicts, the rate multiplier, analysis toggles, and run status.
    """

    def __init__(self, reactor_condition: Mixture, label: str):
        if not isinstance(reactor_condition, Mixture):
            raise TypeError("reactor condition must be a Mixture or Stream "
                            "(reference: reactormodel.py:682)")
        err = reactor_condition.validate()
        if err != 0:
            raise ValueError(
                f"reactor-condition mixture is incomplete (code {err})")
        self._condition = copy.deepcopy(reactor_condition)
        self.label = label
        self._keywords: Dict[str, Keyword] = {}
        self._profiles: Dict[str, Profile] = {}
        self._gasratemultiplier = 1.0
        self._TextOut = False
        self._XMLOut = False
        self.runstatus = STATUS_NOT_RUN
        self._speciesmode = "mass"
        # sensitivity / ROP analysis configuration
        # (reference: reactormodel.py:1522-1621)
        self._sensitivity = False
        self._sensitivity_opts: Dict[str, float] = {}
        self._rop_analysis = False
        self._rop_threshold = 0.0
        # per-solve telemetry filled by concrete run() implementations
        # (see solve_report)
        self._solve_report: Dict = {}
        # raw solution store (reference: reactormodel.py:775-788)
        self._solution_tags = ["time", "distance", "temperature", "pressure",
                               "volume", "velocity", "flowrate"]
        self._numbsolutionpoints = 0
        self._solution_rawarray: Dict[str, np.ndarray] = {}
        self._solution_mixturearray: List[Mixture] = []

    # --- chemistry plumbing -------------------------------------------------
    @property
    def chemID(self) -> int:
        return self._condition.chemID

    @property
    def chemistry(self):
        return self._condition.chemistry

    @property
    def mech(self):
        return self._condition.mech

    @property
    def numbspecies(self) -> int:
        return self._condition.KK

    @property
    def _specieslist(self) -> list:
        return self._condition.species_symbols

    @property
    def reactor_condition(self) -> Mixture:
        return self._condition

    # --- state passthroughs (reference: reactormodel.py:1293-1423) ---------
    @property
    def temperature(self) -> float:
        return self._condition.temperature

    @temperature.setter
    def temperature(self, t: float):
        self._condition.temperature = t

    @property
    def pressure(self) -> float:
        return self._condition.pressure

    @pressure.setter
    def pressure(self, p: float):
        self._condition.pressure = p

    @property
    def X(self) -> np.ndarray:
        return self._condition.X

    @X.setter
    def X(self, recipe):
        self._condition.X = recipe

    @property
    def Y(self) -> np.ndarray:
        return self._condition.Y

    @Y.setter
    def Y(self, recipe):
        self._condition.Y = recipe

    # --- keyword management (reference: reactormodel.py:835-1056) ----------
    def setkeyword(self, key: str, value: KeywordValue):
        """Set or update a keyword (reference: reactormodel.py:861).
        In API mode, protected keywords (TIME, PRES, QLOS, ...) must be
        set through their dedicated property setters; under the
        full-keyword mode (``Keyword.setfullkeywords(True)``) the whole
        deck — protected keywords included — arrives as keyword lines
        (reference: reactormodel.py:116-183)."""
        phrase = str(key).upper()
        if Keyword.noFullKeyword and phrase in Keyword.PROTECTED:
            raise ValueError(
                f"keyword {phrase} is protected; use its dedicated "
                "property/method (reference: reactormodel.py:60-93)")
        self._record_keyword(phrase, value)

    def _record_keyword(self, key: str, value: KeywordValue):
        """Store a keyword without the protected-list check — the path the
        dedicated property setters use."""
        phrase = str(key).upper()
        if phrase in self._keywords:
            self._keywords[phrase].resetvalue(value)
            return
        if isinstance(value, bool):
            kw: Keyword = BooleanKeyword(phrase, value)
        elif isinstance(value, int):
            kw = IntegerKeyword(phrase, value)
        elif isinstance(value, float):
            kw = RealKeyword(phrase, value)
        else:
            kw = StringKeyword(phrase, str(value))
        self._keywords[phrase] = kw

    def getkeyword(self, key: str) -> Optional[KeywordValue]:
        """Value of a set keyword, else None."""
        kw = self._keywords.get(str(key).upper())
        return None if kw is None else kw.value

    def removekeyword(self, key: str):
        """(reference: reactormodel.py:916)."""
        self._keywords.pop(str(key).upper(), None)

    def createkeywordinputlines(self) -> Tuple[int, List[str]]:
        """Render all keywords as deck lines (reference:
        reactormodel.py:966); profiles render after scalars."""
        lines = []
        for kw in self._keywords.values():
            err, line = kw.getvalue_as_string()
            if err == 0 and line:
                lines.append(line)
        for prof in self._profiles.values():
            lines.extend(prof.getprofile_as_string_list()[1])
        return 0, lines

    def showkeywordinputlines(self):
        for line in self.createkeywordinputlines()[1]:
            print(line)

    # --- profiles (reference: reactormodel.py:1057-1187) -------------------
    def setprofile(self, key: str, x, y):
        """Attach or replace a piecewise-linear profile
        (reference: reactormodel.py:1083)."""
        phrase = str(key).upper()
        if phrase in self._profiles:
            self._profiles[phrase].resetprofile(x, y)
        else:
            self._profiles[phrase] = Profile(phrase, x, y)

    def getprofile(self, key: str) -> Optional[Profile]:
        return self._profiles.get(str(key).upper())

    def removeprofile(self, key: str):
        self._profiles.pop(str(key).upper(), None)

    # --- rate multiplier (reference: reactormodel.py:1440) -----------------
    @property
    def gasratemultiplier(self) -> float:
        return self._gasratemultiplier

    @gasratemultiplier.setter
    def gasratemultiplier(self, value: float):
        if value < 0.0:
            raise ValueError("reaction rate multiplier must be >= 0")
        self._gasratemultiplier = float(value)
        self.setkeyword("GFAC", float(value))

    def _effective_mech(self):
        """Mechanism with the gas rate multiplier folded in."""
        mech = self.mech
        if self._gasratemultiplier != 1.0:
            mech = mech.with_rate_multipliers(self._gasratemultiplier)
        return mech

    # --- output toggles (reference: reactormodel.py:1471-1521) -------------
    @property
    def STD_Output(self) -> bool:
        return self._TextOut

    @STD_Output.setter
    def STD_Output(self, mode: bool):
        self._TextOut = bool(mode)
        self.setkeyword("NO_SDOUTPUT_WRITE", not mode)

    @property
    def XML_Output(self) -> bool:
        return self._XMLOut

    @XML_Output.setter
    def XML_Output(self, mode: bool):
        self._XMLOut = bool(mode)
        self.setkeyword("NO_XMLOUTPUT_WRITE", not mode)

    # --- analyses (reference: reactormodel.py:1522-1621) -------------------
    def setsensitivityanalysis(self, mode: bool = True,
                               absolute_tolerance: Optional[float] = None,
                               relative_tolerance: Optional[float] = None,
                               temperature_threshold: Optional[float] = None,
                               species_threshold: Optional[float] = None):
        """Enable A-factor sensitivity analysis (reference:
        reactormodel.py:1522, keywords ASEN/ATLS/RTLS/EPST/EPSS). The
        TPU build computes sensitivities by forward-mode AD at run time."""
        self._sensitivity = bool(mode)
        self.setkeyword("ASEN", bool(mode))
        if absolute_tolerance is not None:
            self._sensitivity_opts["atol"] = float(absolute_tolerance)
            self.setkeyword("ATLS", float(absolute_tolerance))
        if relative_tolerance is not None:
            self._sensitivity_opts["rtol"] = float(relative_tolerance)
            self.setkeyword("RTLS", float(relative_tolerance))
        if temperature_threshold is not None:
            self._sensitivity_opts["temp_threshold"] = float(
                temperature_threshold)
            self.setkeyword("EPST", float(temperature_threshold))
        if species_threshold is not None:
            self._sensitivity_opts["spec_threshold"] = float(
                species_threshold)
            self.setkeyword("EPSS", float(species_threshold))

    def setROPanalysis(self, mode: bool = True,
                       threshold: Optional[float] = None):
        """Enable rate-of-production analysis (reference:
        reactormodel.py:1585, keywords AROP/EPSR)."""
        self._rop_analysis = bool(mode)
        self.setkeyword("AROP", bool(mode))
        if threshold is not None:
            self._rop_threshold = float(threshold)
            self.setkeyword("EPSR", float(threshold))

    # --- full-keyword deck input (reference: reactormodel.py:116-183) ------
    def apply_keyword_deck(self, deck):
        """Apply a text input deck: one 'KEY value...' line per keyword,
        CHEMKIN comment ('!') and END conventions. Repeated
        profile-keyword lines (TPRO/VPRO/...) accumulate into profiles;
        REAC lines set the reactor-condition composition in the current
        species mode. Requires the full-keyword mode to already be ON
        (``Keyword.setfullkeywords(True)``) because the deck may carry
        protected keywords — the exact contract of the reference's
        full-keyword path (batchreactor.py:822).
        """
        if Keyword.noFullKeyword:
            raise RuntimeError(
                "apply_keyword_deck requires the full-keyword mode: "
                "call Keyword.setfullkeywords(True) first "
                "(reference: reactormodel.py:116)")
        if isinstance(deck, str):
            lines = deck.splitlines()
        else:
            lines = list(deck)
        prof_acc: Dict[str, List[Tuple[float, float]]] = {}
        reac: Dict[str, float] = {}
        for raw in lines:
            line = raw.split("!", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            key = parts[0].upper()
            if key == "END":
                break
            if key in Keyword.PROFILE_KEYS and len(parts) >= 3:
                prof_acc.setdefault(key, []).append(
                    (float(parts[1]), float(parts[2])))
                continue
            if key == "REAC" and len(parts) >= 3:
                reac[parts[1]] = float(parts[2])
                continue
            if len(parts) == 1:
                self._record_keyword(key, True)
            else:
                val_s = parts[1]
                try:
                    value: KeywordValue = int(val_s)
                except ValueError:
                    try:
                        value = float(val_s)
                    except ValueError:
                        value = " ".join(parts[1:])
                self._record_keyword(key, value)
        for key, pts in prof_acc.items():
            xs, ys = zip(*pts)
            self.setprofile(key, xs, ys)
        if reac:
            if self._speciesmode == "mole":
                self._condition.X = reac
            else:
                self._condition.Y = reac

    def consume_protected_keywords(self):
        """Route protected keywords captured from a full-keyword deck
        into the typed model state. Every concrete ``run()`` calls this
        first, so deck-configured reactors behave like API-configured
        ones (the reference routes them inside
        __process_keywords_withFullInputs, batchreactor.py:822). Units
        follow the reference's keyword conventions: PRES in atm, TEMP
        K, TIME s, VOL cm^3, heat-transfer keywords CGS."""
        if Keyword.noFullKeyword:
            return
        from ..constants import P_ATM

        v = self.getkeyword("TEMP")
        if v is not None:
            self._condition.temperature = float(v)
        v = self.getkeyword("PRES")
        if v is not None:
            self._condition.pressure = float(v) * P_ATM
        # model-level scalars: keyword -> (attribute, scale); applied
        # only where the concrete model has the attribute
        for key, attr, scale in (
                ("TIME", "time", 1.0),
                ("VOL", "volume", 1.0),
                ("TAU", "residence_time", 1.0),
                ("XEND", "length", 1.0),
                ("FLRT", "mass_flowrate", 1.0),
                ("QLOS", "heat_loss_rate", 1.0),
                ("HTC", "heat_transfer_coefficient", 1.0),
                ("TAMB", "ambient_temperature", 1.0),
                ("AREAQ", "area", 1.0)):
            v = self.getkeyword(key)
            if v is not None:
                prop = getattr(type(self), attr, None)
                settable = hasattr(self, attr) and not (
                    isinstance(prop, property) and prop.fset is None)
                if not settable:
                    logger.warning(
                        "deck keyword %s has no effect on %s", key,
                        type(self).__name__)
                    continue
                setattr(self, attr, float(v) * scale)
        atol, rtol = self.getkeyword("ATOL"), self.getkeyword("RTOL")
        if (atol is not None or rtol is not None) and hasattr(
                self, "tolerances"):
            a0, r0 = self.tolerances
            self.tolerances = (float(atol) if atol is not None else a0,
                               float(rtol) if rtol is not None else r0)

    # --- solution writers (reference: reactormodel.py:1471-1521 ------------
    # STD_Output / XML_Output; the reference's native library writes
    # these during the run, here they are written by process_solution)
    def write_solution_files(self, basename: Optional[str] = None):
        """Write the processed solution as a text file (STD_Output) and
        an XML file (XML_Output), whichever toggles are on. Returns the
        list of paths written."""
        if not self.getrawsolutionstatus():
            raise RuntimeError("no solution available; run() and "
                               "process_solution() first")
        base = basename or (self.label.strip().replace(" ", "_") or
                            "solution")
        written = []
        cols = [t for t in self._solution_tags
                if t in self._solution_rawarray]
        cols += [s for s in self._specieslist
                 if s in self._solution_rawarray]
        n = self._numbsolutionpoints
        if self._TextOut:
            path = base + ".out"
            with open(path, "w") as f:
                f.write("! pychemkin_tpu solution: %s\n" % self.label)
                f.write(" ".join(f"{c:>16s}" for c in cols) + "\n")
                for i in range(n):
                    f.write(" ".join(
                        f"{float(self._solution_rawarray[c][i]):16.8e}"
                        for c in cols) + "\n")
            written.append(path)
        if self._XMLOut:
            import xml.etree.ElementTree as ET

            root = ET.Element("chemkin_solution", label=self.label,
                              points=str(n))
            for c in cols:
                var = ET.SubElement(root, "variable", name=c)
                var.text = " ".join(
                    repr(float(v)) for v in self._solution_rawarray[c])
            path = base + ".xml"
            ET.ElementTree(root).write(path)
            written.append(path)
        return written

    @staticmethod
    def read_solution_file(path: str) -> Dict[str, np.ndarray]:
        """Re-parse a solution file written by
        :meth:`write_solution_files` (text or XML) back into
        {variable: array} — the round-trip the output tests use."""
        if path.endswith(".xml"):
            import xml.etree.ElementTree as ET

            root = ET.parse(path).getroot()
            return {v.get("name"): np.asarray(
                [float(t) for t in (v.text or "").split()])
                for v in root.findall("variable")}
        out: Dict[str, list] = {}
        with open(path) as f:
            rows = [ln for ln in f if not ln.startswith("!")]
        header = rows[0].split()
        data = np.asarray([[float(v) for v in ln.split()]
                           for ln in rows[1:]])
        return {h: data[:, i] for i, h in enumerate(header)}

    # --- composition accessors (reference: reactormodel.py:1330-1423) ------
    @property
    def molefraction(self) -> np.ndarray:
        """Reactor-condition mole fractions (reference:
        reactormodel.py:1330)."""
        return self._condition.X

    @molefraction.setter
    def molefraction(self, recipe):
        self._condition.X = recipe

    @property
    def massfraction(self) -> np.ndarray:
        """Reactor-condition mass fractions (reference:
        reactormodel.py:1365)."""
        return self._condition.Y

    @massfraction.setter
    def massfraction(self, recipe):
        self._condition.Y = recipe

    @property
    def concentration(self) -> np.ndarray:
        """Reactor-condition molar concentrations [mol/cm^3]
        (reference: reactormodel.py:1400)."""
        return self._condition.concentration

    def list_composition(self, mode: str = "mole", bound: float = 0.0):
        """(reference: reactormodel.py:1424)."""
        self._condition.list_composition(mode=mode, bound=bound)

    def setsolutionspeciesfracmode(self, mode: str = "mass"):
        """Species-fraction type for post-processed solutions
        (reference: reactormodel.py:1816)."""
        if mode.lower() not in ("mole", "mass"):
            raise ValueError("species fraction mode must be 'mass' or "
                             "'mole'")
        self._speciesmode = mode.lower()

    # --- reactor-level real-gas toggles (reference: 1622-1719) -------------
    def userealgasEOS(self, mode: bool = True):
        """Enable/disable the cubic EOS for this reactor's chemistry
        set (reference: reactormodel.py:1622)."""
        if mode:
            self.chemistry.use_realgas_cubicEOS()
        else:
            self.chemistry.use_idealgas_law()

    def realgas(self) -> bool:
        """(reference: reactormodel.py:1680)."""
        return bool(self.chemistry.userealgas)

    def setrealgasmixingmodel(self, rule: int = 0):
        """(reference: reactormodel.py:1700)."""
        self.chemistry.set_realgas_mixing_rule(rule)

    # --- run status (reference: reactormodel.py:1720-1764) -----------------
    def getrunstatus(self) -> int:
        return self.runstatus

    def setrunstatus(self, status: int):
        """(reference: reactormodel.py:1745)."""
        self.runstatus = int(status)

    def checkrunstatus(self) -> bool:
        return self.runstatus == STATUS_SUCCESS

    def getrawsolutionstatus(self) -> bool:
        return self._numbsolutionpoints > 0

    def getnumbersolutionpoints(self) -> int:
        """(reference: reactormodel.py:1836)."""
        return self._numbsolutionpoints

    def getmixturesolutionstatus(self) -> bool:
        """(reference: reactormodel.py:1848)."""
        return len(self._solution_mixturearray) > 0

    def run(self) -> int:  # pragma: no cover - abstract template
        """Template method; concrete reactors override
        (reference: reactormodel.py:1792)."""
        raise NotImplementedError

    # --- per-solve telemetry ------------------------------------------------
    def solve_report(self) -> Dict:
        """Per-solve counters of the LAST run(): wall_s, solver work
        (n_steps / n_rejected / n_newton as applicable), success, plus
        model-specific fields. Empty dict before any run. The same dict
        is emitted as a ``solve`` telemetry event and logged through
        :data:`ChemkinLogger` at INFO when the run records it."""
        return dict(self._solve_report)

    @property
    def solve_status(self) -> Optional[int]:
        """Machine-readable :class:`SolveStatus` code of the last
        ``run()`` (None before any run) — the structured reason behind
        a failed ``runstatus``, not just that it failed."""
        return self._solve_report.get("status")

    @property
    def solve_status_name(self) -> Optional[str]:
        """Human/telemetry name of :attr:`solve_status`."""
        return self._solve_report.get("status_name")

    def _record_solve(self, **fields) -> Dict:
        """Store + emit this run's telemetry (concrete ``run()``s call
        this once per solve)."""
        report: Dict = {"model": type(self).__name__, "label": self.label}
        report.update(fields)
        self._solve_report = report
        rec = telemetry.get_recorder()
        rec.event("solve", **report)
        rec.inc("model.solves")
        if not report.get("success", True):
            rec.inc("model.failed_solves")
        sname = report.get("status_name")
        if sname and sname != "OK":
            rec.inc(f"model.status.{sname}")
        logger.info(
            "solve_report %s(%s): %s", type(self).__name__, self.label,
            " ".join(f"{k}={v}" for k, v in report.items()
                     if k not in ("model", "label")))
        return report

    # --- solution plumbing (reference: reactormodel.py:1816-1919) ----------
    def get_solution_variable_profile(self, varname: str) -> np.ndarray:
        """Profile of a state variable ('time', 'temperature', ...) or a
        species symbol (reference: batchreactor.py:1437)."""
        if not self.getrawsolutionstatus():
            raise RuntimeError("no solution available; run() and "
                               "process_solution() first")
        vname = varname.strip()
        if vname.lower() in self._solution_tags:
            return self._solution_rawarray[vname.lower()]
        if vname in self._specieslist:
            return self._solution_rawarray[vname]
        # case-insensitive species fallback
        for s in self._specieslist:
            if s.upper() == vname.upper():
                return self._solution_rawarray[s]
        raise KeyError(f"unknown solution variable {varname!r}")
