"""Spark-ignition engine model with prescribed burn (reference
engines/SI.py:47).

``SIengine`` mirrors the reference's burn-profile surface — Wiebe
parameters (SI.py:141), SOC/duration timing (:180), CA10/50/90 anchor
points (:210), tabulated mass-burned profile (:266), combustion
efficiency (:303) — and drives the two-zone Wiebe-burn kernel
:func:`pychemkin_tpu.ops.engine.solve_si`. The burned-zone inflow is the
complete-combustion product composition from the stoichiometry solver
(the reference computes a burned-product equilibrium inside the native
solver; active burned-zone chemistry here relaxes the products toward
that same equilibrium).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..logger import logger
from ..mixture import Mixture
from ..ops import engine as engine_ops
from ..resilience.status import name_of as status_name_of
from .engine import Engine
from .reactormodel import STATUS_FAILED, STATUS_SUCCESS

#: Wiebe defaults (classic SI values)
_DEFAULT_WIEBE_N = 2.0
_DEFAULT_WIEBE_B = 5.0


class SIengine(Engine):
    """Spark-ignition engine with a prescribed mass-burned profile
    (reference SI.py:47)."""

    def __init__(self, reactor_condition: Mixture,
                 label: Optional[str] = None):
        super().__init__(reactor_condition, label or "SI")
        # burn-profile mode (reference SI.py:95):
        # 0 unset, 1 Wiebe, 2 anchor points, 3 tabulated profile
        self._burnmode = 0
        self.wieben = _DEFAULT_WIEBE_N
        self.wiebeb = _DEFAULT_WIEBE_B
        self.sparktiming = 0.0       # SOC [deg]
        self.burnduration = 0.0      # [deg]
        self.MBpoints = 0
        self.MBangles: Optional[np.ndarray] = None
        self.MBfractions: Optional[np.ndarray] = None
        self.burnefficiency = 1.0
        self._product_min_x = 1e-8
        self._product_names: List[str] = []
        self._fuel_recipe = None
        self._oxid_recipe = None

    # --- burn profile configuration (reference SI.py:141-301) ----------

    def wiebe_parameters(self, n: float, b: float):
        """Wiebe x_b = 1 - exp(-b ((CA-SOC)/dur)^(n+1))
        (reference SI.py:141)."""
        if n <= 0.0 or b <= 0.0:
            raise ValueError("Wiebe function parameters n and b must "
                             "> 0.0.")
        if self._burnmode > 0:
            logger.info("previous burned mass profile setup will be "
                        "overridden.")
        self._burnmode = 1
        self.wieben = float(n)
        self.wiebeb = float(b)

    def set_burn_timing(self, SOC: float, duration: float = 0.0):
        """Start of combustion + burn duration [deg]
        (reference SI.py:180)."""
        if SOC <= self.IVCCA:
            raise ValueError("start of combustion CA must > IVC CA "
                             f"{self.IVCCA}")
        if duration <= 0.0:
            raise ValueError("mass burned duration must > 0.0.")
        self.sparktiming = float(SOC)
        self.burnduration = float(duration)

    def set_burn_anchor_points(self, CA10: float, CA50: float,
                               CA90: float):
        """Fit the Wiebe parameters to the CA10/50/90 anchors
        (reference SI.py:210). With s(x) = -ln(1 - x) the Wiebe curve
        gives s_i = b ((CA_i - SOC)/d)^(n+1); the two anchor RATIOS are
        independent of b and d, so SOC solves a 1-D root problem and
        (n, b, d) follow in closed form (b is pinned by x_b = 0.999 at
        the end of the burn window)."""
        if not CA10 < CA50 < CA90:
            raise ValueError(
                "the anchor points must be given in ascending order.")
        s10, s50, s90 = (-np.log(1 - x) for x in (0.10, 0.50, 0.90))
        r_target = np.log(s50 / s10) / np.log(s90 / s50)

        def ratio(soc):
            m_a = np.log((CA50 - soc) / (CA10 - soc))
            m_b = np.log((CA90 - soc) / (CA50 - soc))
            return m_a / m_b

        # ratio(soc) is monotone in soc: bisect on (far-left, CA10)
        lo = CA10 - 50.0 * (CA90 - CA10)
        hi = CA10 - 1e-9 * (CA90 - CA10)
        f_lo = ratio(lo) - r_target
        for _ in range(200):
            mid = 0.5 * (lo + hi)
            f_mid = ratio(mid) - r_target
            if f_lo * f_mid <= 0:
                hi = mid
            else:
                lo = mid
                f_lo = f_mid
            if hi - lo < 1e-12 * (CA90 - CA10):
                break
        soc = 0.5 * (lo + hi)
        m1 = np.log(s50 / s10) / np.log((CA50 - soc) / (CA10 - soc))
        b = np.log(1000.0)              # x_b = 0.999 at xi = 1
        d = (CA50 - soc) * (b / s50) ** (1.0 / m1)
        self._burnmode = 2
        self.wieben = float(m1 - 1.0)
        self.wiebeb = float(b)
        self.sparktiming = float(soc)
        self.burnduration = float(d)

    def set_mass_burned_profile(self, crankangles, fractions) -> int:
        """Tabulated mass-burned profile (reference SI.py:266): the
        crank angles are NORMALIZED to [0, 1] over the burn window set
        by ``set_burn_timing`` (the reference's own contract: "the crank
        angles must 0 <= and <= 1")."""
        crankangles = np.asarray(crankangles, dtype=np.float64)
        fractions = np.asarray(fractions, dtype=np.float64)
        self.MBpoints = len(crankangles)
        if len(fractions) != self.MBpoints:
            logger.error("data arrays must have the same size.")
            return 1
        if self.MBpoints <= 1:
            logger.error("profile must have more than 1 data pair.")
            return 2
        if crankangles.min() < 0.0 or crankangles.max() > 1.0:
            logger.error("profile crank angles must be normalized to "
                         "[0, 1] over the burn window (reference "
                         "SI.py:266)")
            return 3
        self.MBangles = crankangles
        self.MBfractions = fractions
        self._burnmode = 3
        return 0

    def set_combustion_efficiency(self, efficiency: float):
        """(reference SI.py:303)."""
        if efficiency < 0.0 or efficiency > 1.0:
            raise ValueError("efficiency must > 0.0 and <= 1.0.")
        self.burnefficiency = float(efficiency)
        self.setkeyword("BEFF", float(efficiency))

    def define_fuel_composition(self, recipe):
        """Fuel recipe for the burned-product stoichiometry."""
        self._fuel_recipe = recipe

    def define_oxid_composition(self, recipe):
        self._oxid_recipe = recipe


    def set_burned_products_minimum_mole_fraction(self, x: float = 1e-8):
        """Drop burned-product species below this mole fraction from
        the prescribed product composition (reference SI.py)."""
        if not 0.0 <= x < 1.0:
            raise ValueError("threshold must be in [0, 1)")
        self._product_min_x = float(x)

    def define_product_composition(self, products: List[str]):
        """Complete-combustion product species entering the burned zone."""
        self._product_names = list(products)

    # ------------------------------------------------------------------

    def _burned_products_Y(self) -> np.ndarray:
        """Complete-combustion product mass fractions for the burned-zone
        inflow, from the element-conservation stoichiometry solver
        (utilities.calculate_stoichiometrics) applied to the cylinder
        charge."""
        from ..ops import thermo
        from ..utilities import calculate_stoichiometrics
        import jax.numpy as jnp

        mech = self._effective_mech()
        if not self._product_names:
            raise ValueError(
                "define_product_composition must list the burned "
                "product species (e.g. ['CO2', 'H2O', 'N2'])")
        X0 = np.asarray(self.reactor_condition.X)
        # split the charge into fuel (C/H-bearing) and the rest; the
        # product coefficients come from element conservation
        prod_index = np.array(
            [mech.species_index(s) for s in self._product_names],
            dtype=np.int64)
        # element totals of the whole charge must be carried by products
        ncf = np.asarray(mech.ncf)           # [KK, MM]
        b = ncf.T @ X0                       # element totals
        A = ncf[prod_index].T                # [MM, NP]
        nu, *_ = np.linalg.lstsq(A, b, rcond=None)
        nu = np.clip(nu, 0.0, None)
        Xp = np.zeros(mech.n_species)
        Xp[prod_index] = nu
        if Xp.sum() <= 0:
            raise ValueError("product composition solve failed; check "
                             "the product species list")
        Xp = Xp / Xp.sum()
        # drop trace products below the configured threshold
        # (set_burned_products_minimum_mole_fraction, reference SI.py)
        Xp = np.where(Xp >= self._product_min_x, Xp, 0.0)
        return np.asarray(thermo.X_to_Y(mech, jnp.asarray(Xp / Xp.sum())))

    def _wiebe_tuple(self):
        if self._burnmode == 0:
            raise ValueError("set the burn profile first "
                             "(wiebe_parameters / set_burn_anchor_points"
                             " + set_burn_timing)")
        if self._burnmode in (1, 3) and self.burnduration <= 0.0:
            raise ValueError("set_burn_timing must set SOC and duration")
        if self._burnmode == 3:
            # fit a Wiebe curve to the tabulated profile (least squares
            # in the log-survival domain)
            xi = np.clip(self.MBangles, 1e-6, 1.0)
            xb = np.clip(self.MBfractions, 1e-9, 1.0 - 1e-9)
            mask = (xb > 0.01) & (xb < 0.99)
            if mask.sum() >= 2:
                lx = np.log(xi[mask])
                ls = np.log(-np.log(1.0 - xb[mask]))
                m1, lnb = np.polyfit(lx, ls, 1)
                self.wieben = float(m1 - 1.0)
                self.wiebeb = float(np.exp(lnb))
        return (self.sparktiming, self.burnduration, self.wiebeb,
                self.wieben)

    def run(self) -> int:
        """Integrate IVC -> EVO (reference SI.py run path)."""
        import time as _time

        self.consume_protected_keywords()
        geo = self._geometry()
        ht = self._heat_transfer()
        wiebe = self._wiebe_tuple()
        Yp = self._burned_products_Y()
        rtol, atol = self.tolerances
        t0 = _time.perf_counter()
        sol = engine_ops.solve_si(
            self._effective_mech(), geo,
            T0=self.reactor_condition.temperature,
            P0=self.reactor_condition.pressure,
            Y0=np.asarray(self.reactor_condition.Y),
            start_CA=self.IVCCA, end_CA=self.EVOCA,
            wiebe=wiebe, Y_products=Yp, ht=ht,
            comb_eff=self.burnefficiency,
            rtol=max(rtol, 1e-9), atol=atol)
        self._engine_solution = sol
        ok = bool(sol.success)
        status = int(sol.status)
        self.runstatus = STATUS_SUCCESS if ok else STATUS_FAILED
        self._record_solve(
            wall_s=round(_time.perf_counter() - t0, 6), success=ok,
            status=status, status_name=status_name_of(status),
            n_steps=int(sol.n_steps),
            start_CA=self.IVCCA, end_CA=self.EVOCA)
        return 0 if ok else 1

    def get_mass_burned_fraction(self) -> np.ndarray:
        """x_b(CA) over the saved solution grid."""
        sol = self._engine_solution
        if sol is None:
            raise RuntimeError("please run the engine simulation first.")
        m_tot = float(np.asarray(sol.zone_mass).sum())
        return np.asarray(sol.burned_mass) / m_tot
