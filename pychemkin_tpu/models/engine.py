"""IC-engine model base class (reference engines/engine.py:41).

``Engine`` carries the cylinder geometry, CA<->time conversion, wall
heat-transfer configuration and CA-based output controls; the concrete
engine cycles (HCCI, SI) drive the JAX engine kernels in
:mod:`pychemkin_tpu.ops.engine` where the reference blocks in the native
``KINAll0D_Calculate`` engine problem types.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

from ..logger import logger
from ..mixture import Mixture
from ..ops import engine as engine_ops
from .batch import BatchReactors


class Engine(BatchReactors):
    """Generic engine cylinder model (reference engine.py:41)."""

    #: valid wall heat-transfer correlation keywords
    #: (reference engine.py:96-99)
    _WallHeatTransferModels = ["ICHX", "ICHW", "ICHH"]

    def __init__(self, reactor_condition: Mixture, label: str):
        super().__init__(reactor_condition, label)
        self._numstroke = 4
        self.borediam = 0.0            # [cm]
        self.borearea = 0.0            # [cm2]
        self.enginestroke = 0.0        # [cm]
        self.crankradius = 0.0         # [cm]
        self.connectrodlength = 0.0    # [cm]
        self.pistonoffset = 0.0        # [cm]
        self.cylinderheadarea = 0.0    # [cm2]
        self.pistonheadarea = 0.0      # [cm2]
        self.headareas = 0.0
        self.compressratio = 1.0
        self.enginespeed = 1.0         # RPM
        self.IVCCA = -180.0
        self.EVOCA = 180.0
        self.rundurationCA = 360.0
        self.numbHTmodelparameters = [3, 3, 5]
        self.heattransfermodel: int = -1
        self.heattransferparameters: List[float] = []
        self.cylinderwalltemperature = 298.15
        self.gasvelocity: List[float] = []
        self.HuberIMEP: Optional[float] = None
        self._wallheattransfer = False
        self._engine_solution: Optional[engine_ops.EngineSolution] = None

    # --- CA <-> time (reference engine.py:128-224) ----------------------

    @staticmethod
    def convert_CA_to_Time(CA: float, startCA: float, RPM: float) -> float:
        """t = (CA - CA0)/RPM/6 (reference engine.py:128)."""
        if RPM <= 0.0:
            logger.error("engine speed RPM must > 0.")
            return 0.0
        t = (CA - startCA) / RPM / 6.0
        if t < 0.0:
            logger.error("given CA is less than the starting CA @ IVC.")
            return 0.0
        return t

    @staticmethod
    def convert_Time_to_CA(time: float, startCA: float,
                           RPM: float) -> float:
        """CA = CA0 + 6*RPM*t (reference engine.py:166)."""
        if time < 0.0:
            logger.error("simulation time must > 0.")
            return 0.0
        return startCA + time * RPM * 6.0

    def get_Time(self, CA: float) -> float:
        """(reference engine.py:193)."""
        return self.convert_CA_to_Time(CA, self.IVCCA, self.enginespeed)

    def get_CA(self, time: float) -> float:
        """(reference engine.py:209)."""
        return self.convert_Time_to_CA(time, self.IVCCA, self.enginespeed)

    # --- crank-angle window (reference engine.py:226-330) ---------------

    @property
    def starting_CA(self) -> float:
        """IVC crank angle [deg]."""
        return self.IVCCA

    @starting_CA.setter
    def starting_CA(self, startCA: float):
        self.IVCCA = float(startCA)
        self.rundurationCA = self.EVOCA - self.IVCCA
        self.setkeyword("DEG0", float(startCA))

    @property
    def ending_CA(self) -> float:
        """EVO crank angle [deg]."""
        return self.EVOCA

    @ending_CA.setter
    def ending_CA(self, endCA: float):
        if endCA <= self.IVCCA:
            logger.error("ending CA must exceed the starting CA")
            return
        self.EVOCA = float(endCA)
        self.rundurationCA = self.EVOCA - self.IVCCA
        self.setkeyword("DEGE", float(endCA))

    @property
    def duration_CA(self) -> float:
        return self.rundurationCA

    @duration_CA.setter
    def duration_CA(self, CA: float):
        if CA <= 0.0:
            logger.error("duration must > 0")
            return
        self.rundurationCA = float(CA)
        self.EVOCA = self.IVCCA + float(CA)

    # --- geometry (reference engine.py:332-470) -------------------------

    @property
    def bore(self) -> float:
        """Bore diameter [cm]."""
        return self.borediam

    @bore.setter
    def bore(self, diameter: float):
        if diameter <= 0.0:
            logger.error("bore diameter must > 0")
            return
        self.borediam = float(diameter)
        self.borearea = 0.25 * np.pi * diameter ** 2
        self.setkeyword("BORE", float(diameter))

    @property
    def stroke(self) -> float:
        """Stroke [cm]."""
        return self.enginestroke

    @stroke.setter
    def stroke(self, s: float):
        if s <= 0.0:
            logger.error("stroke must > 0")
            return
        self.enginestroke = float(s)
        self.crankradius = 0.5 * float(s)
        self.setkeyword("STRK", float(s))

    @property
    def connecting_rod_length(self) -> float:
        return self.connectrodlength

    @connecting_rod_length.setter
    def connecting_rod_length(self, s: float):
        if s <= 0.0:
            logger.error("connecting rod length must > 0")
            return
        self.connectrodlength = float(s)
        self.setkeyword("CRLEN", float(s))

    @property
    def compression_ratio(self) -> float:
        return self.compressratio

    @compression_ratio.setter
    def compression_ratio(self, cratio: float):
        if cratio <= 1.0:
            logger.error("compression ratio must > 1")
            return
        self.compressratio = float(cratio)
        self.setkeyword("CMPR", float(cratio))

    @property
    def RPM(self) -> float:
        return self.enginespeed

    @RPM.setter
    def RPM(self, speed: float):
        if speed <= 0.0:
            logger.error("engine speed must > 0")
            return
        self.enginespeed = float(speed)
        self.setkeyword("RPM", float(speed))

    def set_cylinder_head_area(self, area: float):
        """Extra head area beyond the bore cross-section [cm2]
        (reference engine.py:490)."""
        self.cylinderheadarea = max(float(area), 0.0)
        self.headareas = self.cylinderheadarea + self.pistonheadarea

    def set_piston_head_area(self, area: float):
        """(reference engine.py:518)."""
        self.pistonheadarea = max(float(area), 0.0)
        self.headareas = self.cylinderheadarea + self.pistonheadarea

    def set_piston_pin_offset(self, offset: float):
        """(reference engine.py:546)."""
        if abs(offset) >= max(self.crankradius, 1e-12):
            logger.error("piston pin offset distance must < crank radius")
            return
        self.pistonoffset = float(offset)

    def get_clearance_volume(self) -> float:
        """[cm3] (reference engine.py:570)."""
        if self.compressratio <= 1.0:
            logger.error("please set engine compression ratio first.")
            return 0.0
        return self.get_displacement_volume() / (self.compressratio - 1.0)

    def get_displacement_volume(self) -> float:
        """[cm3] (reference engine.py:593)."""
        return self.enginestroke * self.borearea

    def list_engine_parameters(self):
        """(reference engine.py:604)."""
        print("      === engine parameters ===")
        print(f"bore diameter         = {self.borediam} [cm]")
        print(f"stroke                = {self.enginestroke} [cm]")
        print(f"connecting rod length = {self.connectrodlength} [cm]")
        print(f"compression ratio     = {self.compressratio} [-]")
        print(f"engine speed          = {self.enginespeed} [RPM]")
        print(f"IVC crank angle       = {self.IVCCA} [degree]")
        print(f"EVO crank angle       = {self.EVOCA} [degree]")

    # --- CA output controls (reference engine.py:621-713) ---------------

    @property
    def CAstep_for_saving_solution(self) -> float:
        kw = self.getkeyword("DEGSAVE")
        if kw is not None:
            return kw
        return self.rundurationCA / 100.0 if self.rundurationCA > 0 else 0.0

    @CAstep_for_saving_solution.setter
    def CAstep_for_saving_solution(self, delta_CA: float):
        if delta_CA > 0.0:
            self.setkeyword("DEGSAVE", float(delta_CA))
        else:
            logger.error("solution saving CA interval must > 0.")

    @property
    def CAstep_for_printing_solution(self) -> float:
        kw = self.getkeyword("DEGPRINT")
        if kw is not None:
            return kw
        return self.rundurationCA / 100.0 if self.rundurationCA > 0 else 0.0

    @CAstep_for_printing_solution.setter
    def CAstep_for_printing_solution(self, delta_CA: float):
        if delta_CA > 0.0:
            self.setkeyword("DEGPRINT", float(delta_CA))
        else:
            logger.error("solution printing CA interval must > 0.")

    # --- wall heat transfer (reference engine.py:766-924) ---------------

    def set_wall_heat_transfer(self, model: str,
                               HTparameters: List[float],
                               walltemperature: float):
        """Wall heat-transfer correlation (reference engine.py:766):
        'dimensionless' (ICHX: Nu = a Re^b Pr^c), 'dimensional' (ICHW),
        'hohenburg' (ICHH). The TPU build implements the dimensionless
        Nusselt correlation; the other two are accepted and mapped onto
        it with a warning (their leading constants differ)."""
        if self.heattransfermodel >= 0:
            logger.info("previously defined wall heat transfer model "
                        "will be overridden.")
        mymodel = model.lower().rstrip()
        if mymodel == "dimensionless":
            model_id = 0
        elif mymodel in ("dimensional", "dimensioless"):
            model_id = 1
            logger.warning("dimensional correlation is mapped onto the "
                           "dimensionless Nu = a Re^b Pr^c form")
        elif mymodel == "hohenburg":
            model_id = 2
            logger.warning("Hohenburg correlation is mapped onto the "
                           "dimensionless Nu = a Re^b Pr^c form using "
                           "its first three parameters")
        else:
            raise ValueError(
                f"engine wall heat transfer model {model!r} is not "
                "valid; options: 'dimensional', 'dimensionless', "
                "'hohenburg'")
        n_req = self.numbHTmodelparameters[model_id]
        if len(HTparameters) != n_req:
            # validate BEFORE mutating: a failed call must not leave the
            # model half-configured
            raise ValueError(f"{model} requires {n_req} parameters")
        self.heattransfermodel = model_id
        self.heattransferparameters = list(HTparameters)
        self.cylinderwalltemperature = float(walltemperature)
        self._wallheattransfer = True

    def set_gas_velocity_correlation(self, gasvelparameters: List[float],
                                     IMEP: Optional[float] = None):
        """Woschni gas-velocity parameters <C11> <C12> <C2> <swirl>
        (reference engine.py:841)."""
        if self.heattransfermodel < 0:
            raise ValueError(
                "please specify the wall heat transfer model first.")
        if len(gasvelparameters) != 4:
            raise ValueError("gas velocity correlation requires 4 "
                             "parameters: <C11> <C12> <C2> <swirl>")
        if self.gasvelocity:
            logger.info("previously defined gas velocity correlation "
                        "will be overridden.")
        self.gasvelocity = list(gasvelparameters)
        if IMEP is not None:
            self.HuberIMEP = float(IMEP)

    # --- solver-core assembly -------------------------------------------

    def _require_geometry(self):
        missing = []
        if self.borediam <= 0:
            missing.append("bore")
        if self.enginestroke <= 0:
            missing.append("stroke")
        if self.connectrodlength <= 0:
            missing.append("connecting_rod_length")
        if self.compressratio <= 1.0:
            missing.append("compression_ratio")
        if self.enginespeed <= 0:
            missing.append("RPM")
        if missing:
            raise ValueError("engine geometry incomplete; set: "
                             + ", ".join(missing))

    def _geometry(self) -> engine_ops.EngineGeometry:
        self._require_geometry()
        return engine_ops.EngineGeometry(
            bore=self.borediam, stroke=self.enginestroke,
            conrod=self.connectrodlength,
            compression_ratio=self.compressratio,
            rpm=self.enginespeed, piston_offset=self.pistonoffset,
            head_area=self.headareas)

    def _heat_transfer(self):
        if not self._wallheattransfer:
            return None
        p = self.heattransferparameters
        a, b, c = p[0], p[1], p[2]
        kwargs = dict(a=a, b=b, c=c, T_wall=self.cylinderwalltemperature)
        if self.gasvelocity:
            C11, C12, C2, swirl = self.gasvelocity
            kwargs.update(C11=C11, C12=C12, C2=C2, swirl=swirl)
        return engine_ops.WallHeatTransfer(**kwargs)

    # --- solution access -------------------------------------------------


    def get_engine_solution_size(self) -> int:
        """Number of saved solution points in the engine cycle
        (reference engine.py:get_engine_solution_size)."""
        if getattr(self, "_engine_solution", None) is None:
            return 0
        import numpy as np

        return int(len(np.asarray(self._engine_solution.CA)))

    def get_engine_heat_release_CAs(self) -> Tuple[float, float, float]:
        """CA10/CA50/CA90 of cumulative heat release
        (reference engine.py:953)."""
        if self._engine_solution is None:
            raise RuntimeError("please run the engine simulation first.")
        return engine_ops.heat_release_CAs(self._engine_solution)

    def process_engine_solution(self,
                                zoneID: Union[int, None] = None):
        """Per-zone (or zone-0) solution arrays
        (reference engine.py:1067): dict of CA, time, T, P, V, Y."""
        sol = self._engine_solution
        if sol is None:
            raise RuntimeError("please run the engine simulation first.")
        z = 0 if zoneID is None else int(zoneID)
        return {
            "CA": np.asarray(sol.CA),
            "time": np.asarray(sol.times),
            "temperature": np.asarray(sol.T[:, z]),
            "pressure": np.asarray(sol.P),
            "volume": np.asarray(sol.V),
            "mass_fractions": np.asarray(sol.Y[:, z]),
        }

    def process_average_engine_solution(self):
        """Mass-averaged solution across zones
        (reference engine.py:1195)."""
        sol = self._engine_solution
        if sol is None:
            raise RuntimeError("please run the engine simulation first.")
        m_b = np.asarray(sol.burned_mass)
        if np.all(np.isfinite(m_b)):
            # SI: the burned-zone mass grows in time — weight each saved
            # point by the instantaneous (unburned, burned) masses
            m_tot = float(np.asarray(sol.zone_mass).sum())
            w = np.stack([m_tot - m_b, m_b], axis=1) / m_tot  # [n, 2]
        else:
            m = np.asarray(sol.zone_mass)
            w = np.broadcast_to(m / m.sum(),
                                (np.asarray(sol.T).shape[0], m.size))
        T_avg = np.einsum("nz,nz->n", np.asarray(sol.T), w)
        Y_avg = np.einsum("nzk,nz->nk", np.asarray(sol.Y), w)
        return {
            "CA": np.asarray(sol.CA),
            "time": np.asarray(sol.times),
            "temperature": T_avg,
            "pressure": np.asarray(sol.P),
            "volume": np.asarray(sol.V),
            "mass_fractions": Y_avg,
        }
