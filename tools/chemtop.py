#!/usr/bin/env python
"""chemtop — fleet-metrics scraper for serving backends.

Polls the ``metrics`` op of one or more running transport backends
(``pychemkin_tpu/serve/transport.py``) and merges the replies into ONE
fleet snapshot: counters summed, per-tenant in-flight/quota summed,
histograms merged from their RAW bucket states (so fleet p50/p95/p99
come from the merged distribution, not averaged per-process
percentiles), plus a per-backend liveness row (pid, generation —
the supervisor's re-exec stamp, so a churning backend is visible —
and uptime). Backends running the program observatory additionally
contribute a ``programs`` block: per compiled-program wall shares
(from the merged ``program.wall_ms.<id>`` states), analytic
model-FLOP throughput, and ``mfu_pct`` against the fleet's measured
GEMM roof — the "where does the solver wall actually go, and is it
compute" panel.

Three modes:

- ``--once``: one scrape, printed as a JSON line and (with ``--out``)
  banked atomically — the CI/artifact mode; the chaos-soak acceptance
  compares this against the loadgen artifact's per-status counts.
- default (watch): a top(1)-style loop rendering the fleet table every
  ``--interval`` seconds (bank with ``--out`` to keep the latest
  snapshot on disk across a kill). Each poll also feeds the fleet
  health pipeline (``pychemkin_tpu/health``): the snapshot ring turns
  since-boot counters/histograms into windowed rates and true
  last-N-seconds percentiles, the rule engine evaluates the typed
  operator signals (BACKEND_DOWN, ERROR_BUDGET_BURN, ...) with
  hysteresis, and the render grows an alerts panel with a per-signal
  recent-window sparkline. ``--history PATH`` banks one
  ``{"t", "sample", "signals"}`` JSONL entry per poll — the soak
  artifact the check mode replays.
- ``--check-signals H1.jsonl [H2.jsonl ...]``: CI mode, no scraping —
  replay banked histories through a fresh rule engine and print a
  JSON verdict. Exit 1 when any history ends with a FIRING
  severity>=page signal; with ``--require-cycle NAME`` (repeatable)
  exit 0 iff every named signal fired AND cleared in at least one
  history — the chaos-soak gate shape (``run_suite --chaos`` asserts
  the injected SIGKILL produced a fired-then-cleared BACKEND_DOWN).

Usage::

    python tools/chemtop.py --ports 41231 --once --out FLEET.json
    python tools/chemtop.py --ingress 127.0.0.1:8080 --interval 2
    python tools/chemtop.py --ports 41231,41232 --interval 2 \
        --history FLEET_HEALTH.jsonl
    python tools/chemtop.py --check-signals FLEET_HEALTH.jsonl
    python tools/chemtop.py --check-signals obs/health_*.jsonl \
        --require-cycle BACKEND_DOWN
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

# runnable as a script from anywhere (same bootstrap as bench.py)
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pychemkin_tpu import health, knobs, telemetry     # noqa: E402
from pychemkin_tpu.serve.transport import TransportClient  # noqa: E402


def scrape(host: str, port: int, timeout: float = 30.0) -> Dict:
    """One backend's ``metrics`` reply (op/id bookkeeping stripped);
    an unreachable backend yields ``{"port", "error"}`` instead of
    raising — a fleet view must survive one dead member."""
    try:
        client = TransportClient(host, port,
                                 recorder=telemetry.MetricsRecorder())
    except OSError as exc:
        return {"port": port, "error": f"{type(exc).__name__}: {exc}"}
    try:
        reply = dict(client.metrics(timeout=timeout))
    except Exception as exc:  # noqa: BLE001 — dead mid-scrape
        return {"port": port, "error": f"{type(exc).__name__}: {exc}"}
    finally:
        client.close()
    reply.pop("op", None)
    reply.pop("id", None)
    reply["port"] = port
    return reply


def scrape_ingress(url: str, timeout: float = 30.0) -> Dict:
    """One fleet-ingress ``/metrics`` scrape (``pychemkin_tpu/fleet/
    ingress.py``): the reply carries every member's merged metrics
    under ``members`` plus the router's and controller's state — one
    HTTP GET answers for the whole elastic pool. Unreachable ingress
    yields ``{"url", "error"}`` instead of raising."""
    import urllib.request
    if not url.startswith(("http://", "https://")):
        url = f"http://{url}"
    try:
        with urllib.request.urlopen(
                url.rstrip("/") + "/metrics", timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except Exception as exc:  # noqa: BLE001 — scrape must answer
        return {"url": url, "error": f"{type(exc).__name__}: {exc}"}


def merge_fleet(replies: List[Dict]) -> Dict:
    """Merge per-backend ``metrics`` replies into one fleet snapshot
    (pure — unit-testable without sockets). Backends that answered
    with an error still appear in ``backends`` but contribute no
    counters."""
    counters: Dict[str, int] = {}
    tenants: Dict[str, Dict[str, int]] = {}
    hist_states: Dict[str, List[Dict]] = {}
    sched_by_mech: Dict[str, List[Dict]] = {}
    predictor_corr: List[Optional[float]] = []
    prog_by_id: Dict[str, Dict] = {}
    calibrations: List[Dict] = []
    cache_listener = False
    fw_gens: Dict[str, int] = {}
    fw_last_round: Optional[Dict] = None
    backends = []
    for rep in replies:
        row = {"port": rep.get("port"), "pid": rep.get("pid"),
               "generation": rep.get("generation"),
               "uptime_s": rep.get("uptime_s"),
               "error": rep.get("error"),
               "schedule": rep.get("schedule")}
        backends.append(row)
        # a supervisor-side merged reply (Supervisor.metrics) carries
        # its respawn story even when the backend could not answer —
        # fold it BEFORE the error skip: churn counters matter most
        # exactly when the backend is dead/respawning
        sup = rep.get("supervisor")
        if sup:
            for k in ("respawns", "resubmits",
                      "backend_lost_requests"):
                counters[f"supervisor.{k}"] = (
                    counters.get(f"supervisor.{k}", 0)
                    + int(sup.get(k, 0)))
        if rep.get("error"):
            continue
        # per-backend predictor-calibration gauge (None for a legacy
        # or sweep-less backend — rendered n/a, never dropped, so the
        # list stays positional with the alive backends)
        predictor_corr.append(
            (rep.get("gauges") or {}).get("schedule.predictor_corr"))
        for k, v in (rep.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for name, t in (rep.get("tenants") or {}).items():
            agg = tenants.setdefault(name, {"inflight": 0, "quota": 0})
            agg["inflight"] += int(t.get("inflight", 0))
            agg["quota"] += int(t.get("quota", 0))
        for name, state in (rep.get("histogram_states") or {}).items():
            hist_states.setdefault(name, []).append(state)
        for mech, st in (rep.get("schedule") or {}).items():
            sched_by_mech.setdefault(mech, []).append(st)
        # program observatory: program_id is content-addressed (mech
        # signature + kind + shape + resolved knob config), so the
        # same id on two backends IS the same compiled program —
        # metadata from the first carrier, counts summed. Wall comes
        # from the MERGED program.wall_ms.<id> states below, never
        # from averaged per-backend shares.
        prog = rep.get("programs") or {}
        cache_listener = cache_listener or bool(
            prog.get("cache_listener"))
        for pid, row in sorted((prog.get("by_id") or {}).items()):
            agg = prog_by_id.setdefault(pid, {
                "kind": row.get("kind"),
                "mech_sig": row.get("mech_sig"),
                "shape": row.get("shape"),
                "config": row.get("config"),
                "compiles": 0, "dispatches": 0,
                "model_gflop_sum": 0.0,
                "first_compile_ms": None, "cache_source": None,
            })
            agg["compiles"] += int(row.get("compiles", 0))
            agg["dispatches"] += int(row.get("dispatches", 0))
            agg["model_gflop_sum"] += float(
                row.get("model_gflop_sum", 0.0))
            if agg["first_compile_ms"] is None:
                agg["first_compile_ms"] = row.get("first_compile_ms")
            if agg["cache_source"] is None:
                agg["cache_source"] = row.get("cache_source")
        if rep.get("calibration"):
            calibrations.append(rep["calibration"])
        # flywheel facts: incumbent generation per kind is the MAX
        # across backends (promotion fans out; a lagging member shows
        # the fleet as mid-rollout, never as rolled back), last round
        # verdict by timestamp
        for st in (rep.get("flywheel") or {}).values():
            for kind, gen in (st.get("model_gen") or {}).items():
                fw_gens[kind] = max(fw_gens.get(kind, 0), int(gen))
            lr = st.get("last_round")
            if lr and (fw_last_round is None
                       or (lr.get("t") or 0)
                       > (fw_last_round.get("t") or 0)):
                fw_last_round = lr
    # surrogate fast-path gauge: fleet hit rate from the SUMMED
    # counters (never averaged per-backend rates), fallbacks alongside
    # — a dropping hit rate is the signal to retrain/widen the box
    hit = counters.get("serve.surrogate.hit", 0)
    fallback = counters.get("serve.surrogate.fallback", 0)
    surrogate = {
        "hit": hit,
        "miss": counters.get("serve.surrogate.miss", 0),
        "fallback": fallback,
        "hit_rate": (round(hit / (hit + fallback), 4)
                     if hit + fallback else None),
    }
    # flywheel panel: per-kind hit rates from the SUMMED per-kind
    # counter families (the same never-average-rates rule as above),
    # bank/round/promotion tallies, merged incumbent generations
    fw_kinds = sorted(
        {k.rsplit(".", 1)[1] for k in counters
         if k.startswith(("serve.surrogate.hit.",
                          "serve.surrogate.fallback.",
                          "flywheel.banked."))} | set(fw_gens))
    per_kind = {}
    for kind in fw_kinds:
        kh = counters.get(f"serve.surrogate.hit.{kind}", 0)
        kf = counters.get(f"serve.surrogate.fallback.{kind}", 0)
        per_kind[kind] = {
            "hit": kh, "fallback": kf,
            "hit_rate": (round(kh / (kh + kf), 4)
                         if kh + kf else None),
            "banked": counters.get(f"flywheel.banked.{kind}", 0),
            "model_gen": fw_gens.get(kind),
        }
    flywheel = {
        "banked": counters.get("flywheel.banked", 0),
        "rounds": counters.get("flywheel.rounds", 0),
        "promoted": counters.get("flywheel.promoted", 0),
        "rejected": counters.get("flywheel.rejected", 0),
        "shadow_evals": counters.get("flywheel.shadow.evals", 0),
        "errors": counters.get("flywheel.errors", 0),
        "per_kind": per_kind,
        "last_round": fw_last_round,
    }
    histograms = {name: telemetry.merge_histogram_states(states)
                  for name, states in sorted(hist_states.items())}
    # the RAW merged bucket states ride along too: the health layer's
    # snapshot ring subtracts consecutive fleet states to derive true
    # windowed percentiles — summaries alone cannot be differenced
    merged_states = {
        name: telemetry.Histogram.from_states(states).state()
        for name, states in sorted(hist_states.items())}
    # solver panel: the below-dispatch physics a profiled fleet
    # exposes (PYCHEMKIN_SOLVE_PROFILE) — merged solve.* histograms
    # plus the per-backend predictor-calibration gauge. A legacy
    # profile-less backend contributes None entries; the panel (and
    # render) shows n/a instead of crashing the scrape.
    solver = {
        "newton_per_attempt": histograms.get(
            "solve.newton_per_attempt"),
        "dt_min_ns": histograms.get("solve.dt_min_ns"),
        "steps_per_lane": histograms.get("solve.steps_per_lane"),
        "predictor_corr": predictor_corr,
    }
    # adaptive-ladder state per mechanism: window/cap per backend
    # (they adapt independently), ladder from the first answering
    # backend, per-bucket occupancy p50 from the MERGED fleet
    # histograms (serve.occupancy.b<bucket>), never averaged p50s
    schedule: Dict[str, Dict] = {}
    for mech, states in sorted(sched_by_mech.items()):
        ladder = states[0].get("ladder") or []
        per_bucket = {}
        for b in ladder:
            h = histograms.get(f"serve.occupancy.b{b}")
            if h and h.get("count"):
                per_bucket[str(b)] = h.get("p50")
        schedule[mech] = {
            "modes": sorted({s.get("mode") for s in states}),
            "window_ms": [s.get("window_ms") for s in states],
            "max_batch": [s.get("max_batch") for s in states],
            "ladder": list(ladder),
            "bucket_occupancy_p50": per_bucket,
        }
    # program observatory panel: per-program wall from the MERGED
    # program.wall_ms.<id> distributions (state sums are exact, so
    # fleet wall shares come from summed states — never from averaging
    # per-backend percentages), achieved GFLOP/s from the analytic
    # model-FLOP accumulators over that wall, and mfu_pct against the
    # fastest measured GEMM roof among the alive backends (the
    # conservative choice on a heterogeneous fleet: mfu never
    # flatters). Coverage is the acceptance number — attributed
    # program wall over total measured solver wall (serve + sweep).
    roof = max((float(c.get("gemm_gflops", 0.0) or 0.0)
                for c in calibrations), default=0.0) or None
    attributed_wall = 0.0
    for pid, agg in prog_by_id.items():
        h = histograms.get(f"program.wall_ms.{pid}") or {}
        wall_ms = float(h.get("sum", 0.0) or 0.0)
        agg["wall_ms"] = round(wall_ms, 3)
        attributed_wall += wall_ms
        gflop = agg["model_gflop_sum"]
        agg["achieved_gflops"] = (
            round(gflop / (wall_ms / 1e3), 3)
            if wall_ms > 0 and gflop > 0 else None)
        agg["mfu_pct"] = (
            round(100.0 * agg["achieved_gflops"] / roof, 3)
            if agg["achieved_gflops"] is not None and roof else None)
    for agg in prog_by_id.values():
        agg["wall_share"] = (round(agg["wall_ms"] / attributed_wall, 4)
                             if attributed_wall > 0 else None)
    solver_wall = sum(
        float((histograms.get(name) or {}).get("sum", 0.0) or 0.0)
        for name in ("serve.solve_ms", "sweep.solve_ms"))
    programs = {
        "by_id": prog_by_id,
        "attributed_wall_ms": round(attributed_wall, 3),
        "solver_wall_ms": round(solver_wall, 3),
        "coverage": (round(attributed_wall / solver_wall, 4)
                     if solver_wall > 0 else None),
        "roof_gflops": roof,
        "cache_listener": cache_listener,
    }
    return {
        "t": time.time(),
        "n_backends": len(backends),
        "n_alive": sum(1 for b in backends if not b["error"]),
        "backends": backends,
        "counters": counters,
        "tenants": tenants,
        "surrogate": surrogate,
        "flywheel": flywheel,
        "schedule": schedule,
        "solver": solver,
        "programs": programs,
        "calibration": calibrations,
        "histograms": histograms,
        "histogram_states": merged_states,
    }


def render(snapshot: Dict, view=None, signals=None,
           fleet: Optional[Dict] = None) -> str:
    """Human top-style view of one merged snapshot. ``view`` (a
    health ``WindowView`` from the watch loop's ring) adds windowed
    trends — notably the fleet ``predictor_corr`` latest vs
    window-start; ``signals`` (the engine's per-signal state) adds
    the alerts panel with a per-signal recent sparkline; ``fleet``
    (the ingress reply's ``router``/``controller`` blocks) adds the
    fleet-controller panel — pool vs bounds, routing spread, and the
    recent typed ``fleet.action`` decisions."""
    lines = [f"chemtop — {snapshot['n_alive']}/"
             f"{snapshot['n_backends']} backends alive"]
    if fleet:
        ctl = fleet.get("controller") or {}
        rt = fleet.get("router") or {}
        if ctl:
            lines.append(
                f"  fleet: pool {ctl.get('pool_size')} "
                f"[{ctl.get('min_size')}..{ctl.get('max_size')}]  "
                f"cooldown {ctl.get('cooldown_remaining_s', 0):.0f}"
                f"/{ctl.get('cooldown_s', 0):.0f}s  "
                f"idle_streak {ctl.get('idle_streak')}  "
                f"actions {ctl.get('n_actions', 0)}")
            for act in (ctl.get("recent_actions") or [])[-4:]:
                lines.append(
                    f"    action {act.get('action')} "
                    f"{act.get('member')}  reason "
                    f"{act.get('reason')}  pool "
                    f"{act.get('pool_size')}")
        if rt:
            spread = "  ".join(
                f"{m}={n}" for m, n in
                sorted((rt.get("assigned") or {}).items()))
            draining = ",".join(rt.get("draining") or []) or "-"
            lines.append(
                f"  router: reroutes {rt.get('reroutes', 0)}  "
                f"rejected {rt.get('rejected', 0)}  "
                f"draining {draining}"
                + (f"  assigned {spread}" if spread else ""))
            # gray-failure economics (ISSUE 19): hedge counters, any
            # tripped breaker, any member the outlier detector holds
            # MEMBER_DEGRADED for — silent when the fleet is clean
            hedge = rt.get("hedge") or {}
            if any(hedge.values()):
                lines.append(
                    f"  hedge: issued {hedge.get('issued', 0)}  "
                    f"won {hedge.get('won', 0)}  "
                    f"wasted {hedge.get('wasted', 0)}")
            for mid, br in sorted((rt.get("breakers") or {}).items()):
                if br.get("state") == "closed" \
                        and not br.get("n_trips"):
                    continue
                lines.append(
                    f"  breaker {mid}: {br.get('state')}  "
                    f"trips {br.get('n_trips', 0)}  "
                    f"probes {br.get('probes_done', 0)}")
            for mid, o in sorted((rt.get("outliers") or {}).items()):
                if not o.get("firing"):
                    continue
                lines.append(
                    f"  DEGRADED {mid}: p99 {o.get('p99_ms')}ms vs "
                    f"median {o.get('median_ms')}ms "
                    f"(x{o.get('ratio')})")
    for sig in (signals or []):
        if sig["state"] != "firing":
            continue
        ev = "  ".join(f"{k}={v}" for k, v in
                       sorted((sig.get("evidence") or {}).items()))
        lines.append(
            f"  ALERT [{sig['severity']}] {sig['signal']} "
            f"{sig.get('recent', '')}"
            + (f"  {ev}" if ev else ""))
    for b in snapshot["backends"]:
        state = (f"ERROR {b['error']}" if b["error"] else
                 f"pid {b['pid']}  gen {b['generation']}  "
                 f"up {b['uptime_s']:.0f}s")
        lines.append(f"  :{b['port']}  {state}")
    c = snapshot["counters"]
    lines.append(
        f"  requests {c.get('serve.requests', 0)}  "
        f"batches {c.get('serve.batches', 0)}  "
        f"compiles {c.get('serve.compiles', 0)}  "
        f"rejected {c.get('serve.rejected', 0) + c.get('serve.tenant_rejected', 0)}  "
        f"rescued {c.get('serve.rescued', 0)}  "
        f"deadline_expired {c.get('serve.deadline_expired', 0)}")
    sur = snapshot.get("surrogate") or {}
    if (sur.get("hit", 0) + sur.get("fallback", 0)
            + sur.get("miss", 0)):
        rate = sur.get("hit_rate")
        lines.append(
            f"  surrogate: hit {sur['hit']}  miss {sur['miss']}  "
            f"fallback {sur['fallback']}  "
            f"hit_rate {'n/a' if rate is None else f'{rate:.1%}'}")
    fw = snapshot.get("flywheel") or {}
    if fw.get("banked") or fw.get("rounds") or fw.get("per_kind"):
        lines.append(
            f"  flywheel: banked {fw.get('banked', 0)}  "
            f"rounds {fw.get('rounds', 0)}  "
            f"promoted {fw.get('promoted', 0)}  "
            f"rejected {fw.get('rejected', 0)}  "
            f"shadow_evals {fw.get('shadow_evals', 0)}")
        for kind, row in sorted((fw.get("per_kind") or {}).items()):
            r = row.get("hit_rate")
            gen = row.get("model_gen")
            lines.append(
                f"    {kind}: hit_rate "
                f"{'n/a' if r is None else f'{r:.1%}'}  "
                f"banked {row.get('banked', 0)}  "
                f"gen {'n/a' if gen is None else gen}")
        lr = fw.get("last_round")
        if lr:
            lines.append(
                f"    last_round: {lr.get('req_kind')} "
                f"{lr.get('verdict')} gen {lr.get('model_gen')}")
    for mech, s in sorted((snapshot.get("schedule") or {}).items()):
        occ = "  ".join(f"b{b}={p:.3g}" for b, p in
                        sorted(s["bucket_occupancy_p50"].items(),
                               key=lambda kv: int(kv[0]))
                        if p is not None)
        windows = "/".join(f"{w:g}ms" for w in s["window_ms"]
                           if w is not None)
        lines.append(
            f"  schedule[{mech}]: "
            f"{'/'.join(m for m in s['modes'] if m)}  "
            f"window {windows}  "
            f"cap {'/'.join(str(c) for c in s['max_batch'])}  "
            f"ladder {s['ladder']}"
            + (f"  occ_p50 {occ}" if occ else ""))
    sol = snapshot.get("solver") or {}
    corr = [c for c in (sol.get("predictor_corr") or [])
            if c is not None]
    has_series = any((sol.get(k) or {}).get("count")
                     for k in ("newton_per_attempt", "dt_min_ns",
                               "steps_per_lane"))
    if has_series or corr:
        # the solver panel: per-lane physics merged fleet-wide.
        # Missing series (a legacy profile-less backend, or the knob
        # off) render as n/a — a mixed fleet must stay scrapeable.
        def _p50(key):
            h = sol.get(key)
            return (f"{h['p50']:.3g}" if h and h.get("count")
                    else "n/a")

        corr_txt = ("/".join(f"{c:+.2f}" for c in corr)
                    if corr else "n/a")
        # windowed trend of the fleet calibration gauge: latest vs
        # window-start (ISSUE 15 fix — the point values alone cannot
        # show decay). Legacy schedule-less backends stay n/a.
        trend_txt = ""
        if view is not None:
            start, latest = view.gauge_trend("schedule.predictor_corr")
            if latest is not None:
                delta = (f"  Δ{latest - start:+.2f}"
                         f"/{view.duration_s:.0f}s"
                         if start is not None else "")
                trend_txt = f"  fleet {latest:+.2f}{delta}"
        lines.append(
            f"  solver: newton/attempt p50 {_p50('newton_per_attempt')}"
            f"  dt_min p50 {_p50('dt_min_ns')}ns"
            f"  steps/lane p50 {_p50('steps_per_lane')}"
            f"  predictor_corr {corr_txt}{trend_txt}")
    prog = snapshot.get("programs") or {}
    by_id = prog.get("by_id") or {}
    if by_id:
        cov = prog.get("coverage")
        roof = prog.get("roof_gflops")
        lines.append(
            f"  programs: {len(by_id)}  "
            f"wall {prog.get('attributed_wall_ms', 0):.0f}"
            f"/{prog.get('solver_wall_ms', 0):.0f}ms  "
            f"coverage {'n/a' if cov is None else f'{cov:.1%}'}  "
            f"roof {'n/a' if not roof else f'{roof:.1f}'} GF/s  "
            f"cache_listener "
            f"{'on' if prog.get('cache_listener') else 'off'}")
        ranked = sorted(by_id.items(),
                        key=lambda kv: -(kv[1].get("wall_ms") or 0.0))
        for pid, p in ranked[:8]:
            shape = "x".join(str(s) for s in (p.get("shape") or ()))
            share = p.get("wall_share")
            gfs = p.get("achieved_gflops")
            mfu = p.get("mfu_pct")
            src = p.get("cache_source") or "-"
            lines.append(
                f"    {pid}  {p.get('kind')}[{shape}]  "
                f"{'n/a' if share is None else f'{share:.1%}'} "
                f"of wall ({p.get('wall_ms', 0):.0f}ms/"
                f"{p.get('dispatches', 0)}d)  "
                f"{'n/a' if gfs is None else f'{gfs:.2f}'} GF/s  "
                f"mfu {'n/a' if mfu is None else f'{mfu:.1f}%'}  "
                f"compiles {p.get('compiles', 0)}({src})")
        if len(ranked) > 8:
            rest = sum(p.get("wall_ms") or 0.0
                       for _, p in ranked[8:])
            lines.append(f"    (+{len(ranked) - 8} more programs, "
                         f"{rest:.0f}ms)")
    for name in ("serve.queue_wait_ms", "serve.solve_ms"):
        h = snapshot["histograms"].get(name)
        if h and h.get("count"):
            lines.append(
                f"  {name}: n={h['count']}  p50={h['p50']:.3g}  "
                f"p95={h['p95']:.3g}  p99={h['p99']:.3g}")
    for name, t in sorted(snapshot["tenants"].items()):
        lines.append(f"  tenant {name}: inflight {t['inflight']}"
                     f"/{t['quota']}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--ports", default=None,
                   help="comma list of backend ports to scrape "
                        "(required unless --check-signals or "
                        "--ingress)")
    p.add_argument("--ingress", default=None, metavar="HOST:PORT",
                   help="scrape a fleet HTTP ingress /metrics "
                        "endpoint instead of TCP backends; adds the "
                        "fleet-controller panel (pool vs bounds, "
                        "recent fleet.action decisions, routing "
                        "spread)")
    p.add_argument("--once", action="store_true",
                   help="one scrape: JSON line to stdout (CI mode)")
    p.add_argument("--out", default=None,
                   help="bank the merged snapshot here (atomic "
                        "rewrite, every poll)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll interval in watch mode, s")
    p.add_argument("--iterations", type=int, default=None,
                   help="stop watch mode after N polls (default: "
                        "until interrupted)")
    p.add_argument("--timeout", type=float, default=30.0,
                   help="per-backend scrape timeout, s")
    p.add_argument("--history", default=None,
                   help="watch mode: bank one {t, sample, signals} "
                        "JSONL entry per poll (the --check-signals "
                        "artifact)")
    p.add_argument("--window", type=float, default=None,
                   help="health window seconds (default: the "
                        "PYCHEMKIN_HEALTH_WINDOW_S knob)")
    p.add_argument("--check-signals", nargs="+", default=None,
                   metavar="HISTORY",
                   help="CI mode: replay banked history JSONL "
                        "file(s) through a fresh rule engine; rc 1 "
                        "on any history ending with a firing "
                        "severity>=page signal")
    p.add_argument("--require-cycle", action="append", default=[],
                   metavar="SIGNAL",
                   help="with --check-signals: rc 0 iff each named "
                        "signal fired AND cleared in at least one "
                        "history (the chaos-soak gate)")
    return p


def check_signals(paths: List[str], require_cycle: List[str]) -> Dict:
    """Replay banked health histories (pure: no sockets). Returns the
    verdict dict ``main`` prints; ``rc`` inside is the process exit
    code — with ``require_cycle`` the gate is cycle presence, else no
    history may END with a firing severity>=page signal."""
    per_file = {}
    cycled = set()
    firing_page = {}
    for path in paths:
        entries = list(telemetry.read_jsonl(path))
        samples = [e.get("sample") for e in entries
                   if isinstance(e.get("sample"), dict)]
        verdict = health.replay(samples)
        per_file[path] = {
            "n_samples": verdict["n_samples"],
            "firing_page": verdict["firing_page"],
            "cycles": verdict["cycles"],
            "transitions": [
                {"t": ev["t"], "signal": ev["signal"],
                 "state": ev["state"]}
                for ev in verdict["timeline"]],
        }
        cycled.update(name for name, ok in verdict["cycles"].items()
                      if ok)
        if verdict["firing_page"]:
            firing_page[path] = verdict["firing_page"]
    missing = [name for name in require_cycle if name not in cycled]
    if require_cycle:
        rc = 1 if missing else 0
    else:
        rc = 1 if firing_page else 0
    return {"mode": "check-signals", "rc": rc,
            "files": per_file, "cycled": sorted(cycled),
            "require_cycle": require_cycle,
            "missing_cycles": missing,
            "firing_page": firing_page}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.check_signals:
        verdict = check_signals(args.check_signals,
                                args.require_cycle)
        print(json.dumps(verdict), flush=True)
        return verdict["rc"]
    if not args.ports and not args.ingress:
        print("chemtop: --ports or --ingress is required (or "
              "--check-signals)", file=sys.stderr)
        return 2
    ports = [int(x) for x in (args.ports or "").split(",")
             if x.strip()]

    def poll():
        """One poll: (per-backend metrics replies, fleet blocks)."""
        if args.ingress:
            doc = scrape_ingress(args.ingress, args.timeout)
            if doc.get("error"):
                return [doc], None
            replies = []
            for mid, rep in sorted((doc.get("members") or {}).items()):
                rep = dict(rep)
                # the backend-row key: members have ids, not ports
                rep.setdefault("port", mid)
                replies.append(rep)
            return replies, {"router": doc.get("router"),
                             "controller": doc.get("controller")}
        return [scrape(args.host, port, args.timeout)
                for port in ports], None
    window_s = (args.window if args.window is not None
                else knobs.value("PYCHEMKIN_HEALTH_WINDOW_S"))
    # the watch loop's health pipeline: ring + rule engine over the
    # merged snapshots; signal transitions land on a local recorder
    # (and in --history entries) rather than a backend's sink
    ring = health.SnapshotRing(
        cap=knobs.value("PYCHEMKIN_HEALTH_RING"))
    engine = health.HealthEngine(recorder=telemetry.MetricsRecorder())
    n = 0
    while True:
        replies, fleet = poll()
        snapshot = merge_fleet(replies)
        if fleet:
            snapshot["fleet"] = fleet
        if args.out:
            telemetry.atomic_write_json(args.out, snapshot)
        if args.once:
            print(json.dumps(snapshot), flush=True)
            return 0 if (snapshot["n_alive"] > 0 if args.ingress
                         else snapshot["n_alive"] == len(ports)) else 1
        sample = ring.append(health.normalize_sample(snapshot))
        signals = engine.evaluate(ring)
        if args.history:
            telemetry.append_jsonl(args.history,
                                   {"t": sample["t"],
                                    "sample": sample,
                                    "signals": signals})
        print(render(snapshot, view=ring.window(window_s),
                     signals=signals, fleet=fleet), flush=True)
        n += 1
        if args.iterations is not None and n >= args.iterations:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
