#!/usr/bin/env python
"""Open-loop Poisson load generator for the online serving layer.

Drives a :class:`pychemkin_tpu.serve.ChemServer` with a seeded Poisson
request stream (open loop: arrivals keep their schedule regardless of
completions, so queueing collapse is visible instead of self-throttled
away) and banks a JSON latency artifact with the same atomic
tmp+rename idiom as the bench (a kill mid-run leaves either the
previous artifact or a complete new one, never a torn file).

Two targets:

- default: the in-process server (the PR 5 latency harness);
- ``--transport``: a SUPERVISED backend process driven over the
  JSON-over-TCP socket (``pychemkin_tpu/serve/transport.py`` behind
  ``serve/supervisor.py``) — the chaos-soak harness. ``--chaos`` puts
  a ``PYCHEMKIN_PROC_FAULTS`` spec into the backend child only (e.g.
  ``'[{"mode": "kill_backend_at_request", "request": 20}]'`` SIGKILLs
  it mid-load), and the artifact then banks the supervisor's
  respawn/re-submit counters next to the per-status counts — the
  acceptance evidence that every admitted request resolved.

``--stiffness-mix`` widens the ignition-family payload draw to a
broad (T0, phi) box (each request gets its own equivalence-ratio
composition) so the soak offers genuinely mixed-stiffness batches;
the artifact then records the mix ranges plus a per-cohort
(cool/mid/hot initial-temperature tercile) latency split and the
server's live schedule state (mode, window, per-bucket occupancy).

Usage::

    python tools/loadgen.py --mech h2o2 --kinds equilibrium,ignition \
        --rate 100 --n 200 --seed 0 --out LOADGEN.json
    python tools/loadgen.py --kinds ignition --stiffness-mix \
        --rate 50 --n 120 --out MIX.json
    python tools/loadgen.py --transport --deadline-ms 60000 \
        --chaos '[{"mode": "kill_backend_at_request", "request": 20}]' \
        --rate 50 --n 100 --out SOAK.json
    python tools/loadgen.py --fleet 3 --fleet-http --rate 50 --n 150 \
        --chaos '[{"mode": "kill_backend_at_request", "request": 20}]' \
        --deadline-ms 60000 --out FLEET_SOAK.json

The artifact carries the request-side latency distribution
(p50/p95/p99/mean/max ms), occupancy, rejection/timeout/rescue counts,
per-status counts, plus the server-side telemetry snapshot (in-process)
or the supervisor + backend stats (transport).

Observability (ISSUE 8): every run also banks an ``--obs-dir``
(default ``<out stem>_obs/``) holding the crash-safe JSONL sinks —
``client.jsonl`` (client/supervisor-side events incl. ``trace.span``
wire/resubmit spans) and, in transport mode, ``backend.jsonl`` (the
backend child's serve-layer spans, appended across respawned
generations) — plus any supervisor kill reports and backend flight
dumps. The artifact's ``trace_exemplars`` block names the slowest /
stuck requests' trace ids with per-stage span breakdowns assembled
from those sinks; follow one with::

    grep <trace-id> <obs-dir>/*.jsonl

and the artifact's ``metrics`` block (transport mode) is the same
merged snapshot ``tools/chemtop.py`` scrapes live.

Fleet health (ISSUE 15): in transport mode the supervisor's embedded
health monitor banks ``health.jsonl`` in the obs dir (one
``{"t", "sample", "signals"}`` entry per sample — replay with
``python tools/chemtop.py --check-signals <obs>/health.jsonl``), and
the artifact's ``health`` block carries the evaluated signal state
plus the fire/clear transition timeline: a chaos soak shows its
``BACKEND_DOWN`` fired-then-cleared cycle right in the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# runnable as a script from anywhere: the repo root is the package's
# parent, same bootstrap as bench.py
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pychemkin_tpu import serve, telemetry          # noqa: E402
from pychemkin_tpu.mechanism import load_embedded   # noqa: E402
from pychemkin_tpu.serve import loadgen             # noqa: E402
from pychemkin_tpu.serve.supervisor import Supervisor  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   help="embedded mechanism name (default h2o2)")
    p.add_argument("--kinds", default="equilibrium",
                   help="comma list of request kinds (ignition, psr, "
                        "equilibrium, surrogate_ignition, "
                        "surrogate_equilibrium)")
    p.add_argument("--surrogate-model", default=None,
                   help="trained model npz (tools/train_surrogate.py) "
                        "— required when --kinds names a surrogate_* "
                        "kind; enables a mixed surrogate/solver "
                        "stream")
    p.add_argument("--stiffness-mix", action="store_true",
                   help="draw ignition-family payloads over a WIDE "
                        "(T0, phi) range so the soak exercises mixed-"
                        "stiffness batches; the artifact records the "
                        "mix ranges and a per-cohort (cool/mid/hot "
                        "initial-T tercile) latency split")
    p.add_argument("--ood-mix", action="store_true",
                   help="draw surrogate-family payloads OUTSIDE the "
                        "default trained box on one axis (hotter T0 "
                        "for ignition/equilibrium, longer tau for "
                        "psr): round-0 traffic is all verified "
                        "fallback, so every miss banks a label where "
                        "the next retrain needs one")
    p.add_argument("--flywheel-rounds", type=int, default=None,
                   metavar="R",
                   help="flywheel soak mode: run R rounds of "
                        "initially-OOD traffic (implies --ood-mix) "
                        "against an in-process server with the miss "
                        "bank + retrain daemon attached; each round "
                        "bursts traffic, feeds the health monitor, "
                        "lets SURROGATE_RETRAIN drive a retrain + "
                        "shadow + promote cycle, then banks the "
                        "per-kind hit-rate climb — plus a final "
                        "scrambled-labels chaos round that must be "
                        "shadow-rejected — into the artifact")
    p.add_argument("--flywheel-burst", type=int, default=24,
                   help="requests per kind per flywheel burst")
    p.add_argument("--rate", type=float, default=100.0,
                   help="offered arrival rate, requests/s")
    p.add_argument("--n", type=int, default=200,
                   help="number of arrivals to offer")
    p.add_argument("--seed", type=int, default=0,
                   help="rng seed (arrival schedule + payloads)")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--delay-ms", type=float, default=2.0)
    p.add_argument("--buckets", default="1,8,32",
                   help="comma list of bucket sizes")
    p.add_argument("--queue-depth", type=int, default=256)
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-future result timeout, s")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline budget, ms")
    p.add_argument("--out", default="LOADGEN.json",
                   help="artifact path (atomic rewrite)")
    p.add_argument("--obs-dir", default=None,
                   help="observability dir for JSONL trace sinks, kill "
                        "reports, flight dumps (default: <out>_obs/)")
    p.add_argument("--exemplars", type=int, default=5,
                   help="slowest/stuck trace exemplars in the artifact")
    # -- supervised transport soak mode --------------------------------
    p.add_argument("--transport", action="store_true",
                   help="drive a SUPERVISED backend process over the "
                        "socket transport instead of in-process")
    p.add_argument("--tenant", default="default",
                   help="transport tenant id to submit as")
    p.add_argument("--quota", type=int, default=256,
                   help="per-tenant in-flight admission quota")
    p.add_argument("--chaos", default=None,
                   help="PYCHEMKIN_PROC_FAULTS JSON injected into the "
                        "backend child only (chaos soak)")
    p.add_argument("--retry-budget", type=int, default=1,
                   help="supervisor re-sends per request after a "
                        "backend loss")
    p.add_argument("--max-respawns", type=int, default=None,
                   help="supervisor backend respawn budget")
    # -- elastic fleet soak mode ----------------------------------------
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="fleet mode: N supervised backends behind the "
                        "mechanism-aware router (pychemkin_tpu/fleet) "
                        "with the signal-driven controller; all "
                        "members share one staging + XLA cache dir so "
                        "scale-up/replace costs zero new compiles. "
                        "--chaos then injects the fault into the "
                        "FIRST member only, with respawn budget 0 — "
                        "its death exercises the typed BACKEND_LOST "
                        "re-route + controller replace path")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="controller max pool size (default N+1)")
    p.add_argument("--fleet-http", action="store_true",
                   help="drive the fleet over the HTTP ingress front "
                        "door instead of the in-process router")
    p.add_argument("--fleet-poll-s", type=float, default=0.5,
                   help="controller reconciliation poll interval, s")
    return p


def _engine_config() -> dict:
    return {"ignition": {"rtol": 1e-6, "atol": 1e-10,
                         "max_steps_per_segment": 4000}}


def _surrogate_config(args, kinds, cfg) -> dict:
    """Add the surrogate entries to engine config ``cfg`` (validated
    against --surrogate-model). Both paths use the JSON-safe
    ``share_base_kind`` wiring: the (local or backend-side) ChemServer
    resolves it to ITS base engine instance, so warmup compiles the
    stiff program once and fallbacks bit-match ``solve_direct`` of
    the base kind."""
    surrogate_kinds = [k for k in kinds
                       if k.startswith(loadgen.SURROGATE_PREFIX)]
    if surrogate_kinds and not args.surrogate_model:
        raise SystemExit(
            f"--kinds includes {surrogate_kinds} but no "
            "--surrogate-model was given (train one with "
            "tools/train_surrogate.py)")
    for kind in surrogate_kinds:
        cfg[kind] = {
            "model_path": args.surrogate_model,
            "share_base_kind": kind[len(loadgen.SURROGATE_PREFIX):]}
    return cfg


class _Obs:
    """The run's observability surface: one dir holding the client (and
    in transport mode, backend) JSONL sinks, kill reports, and flight
    dumps — everything the artifact's trace exemplars are assembled
    from, and everything a human greps a trace id across."""

    def __init__(self, args):
        self.dir = args.obs_dir or (
            os.path.splitext(args.out)[0] + "_obs")
        os.makedirs(self.dir, exist_ok=True)
        self.client_jsonl = os.path.join(self.dir, "client.jsonl")
        self.backend_jsonl = os.path.join(self.dir, "backend.jsonl")
        self.health_jsonl = os.path.join(self.dir, "health.jsonl")
        # one run = one story: a reused obs dir must not bleed a
        # previous run's spans into this run's exemplars, nor its
        # post-mortems into this artifact's kill/flight lists, nor a
        # stale health timeline into this run's signal verdict
        for path in (self.client_jsonl, self.backend_jsonl,
                     self.health_jsonl):
            if os.path.exists(path):
                os.unlink(path)
        self._t0 = time.time()
        self.recorder = telemetry.MetricsRecorder(
            sink=telemetry.JsonlSink(self.client_jsonl))

    def trace_events(self):
        """All trace.span events banked so far, across every sink."""
        events = []
        for path in (self.client_jsonl, self.backend_jsonl):
            if os.path.exists(path):
                events.extend(e for e in telemetry.read_jsonl(path)
                              if e.get("kind") == "trace.span")
        return events

    def artifacts(self) -> dict:
        import glob as _glob

        def _this_run(pattern):
            # mtime-gated (small slack for clock granularity): stale
            # post-mortems from an earlier run in the same dir are a
            # different story, not this artifact's evidence
            return sorted(
                p for p in _glob.glob(os.path.join(self.dir, pattern))
                if os.path.getmtime(p) >= self._t0 - 1.0)

        return {
            "obs_dir": self.dir,
            "kill_reports": _this_run("kill_report*.json"),
            "flight_records": _this_run("flight_*.json"),
        }


def _run_inprocess(args, kinds, bucket_sizes, rng, samplers, obs,
                   classify=None):
    mech = load_embedded(args.mech)
    rec = obs.recorder
    server = serve.ChemServer(
        mech, bucket_sizes=bucket_sizes, max_batch_size=args.max_batch,
        max_delay_ms=args.delay_ms, queue_depth=args.queue_depth,
        recorder=rec,
        engine_config=_surrogate_config(args, kinds,
                                        _engine_config()))
    print(f"# loadgen: warming {kinds} over buckets {bucket_sizes}",
          file=sys.stderr)
    warm = server.warmup(kinds)
    with server:
        summary = loadgen.run_load(
            server, samplers, rate_hz=args.rate, n_requests=args.n,
            rng=rng, result_timeout_s=args.timeout,
            deadline_ms=args.deadline_ms,
            trace_events=obs.trace_events,
            n_exemplars=args.exemplars, classify=classify)
        sched = server.schedule_state()
    return summary, {"warmup_compiles": warm,
                     "schedule": sched,
                     "telemetry": rec.snapshot()}


def _run_transport(args, kinds, bucket_sizes, rng, samplers, obs,
                   classify=None):
    if args.chaos is not None:
        json.loads(args.chaos)       # fail fast on a typo'd spec
    rec = obs.recorder
    engine_config = _surrogate_config(args, kinds, _engine_config())
    config = {
        "tenants": {args.tenant: {"mech": args.mech,
                                  "quota": args.quota}},
        "kinds": kinds,
        "chem": {"bucket_sizes": list(bucket_sizes),
                 "max_batch_size": args.max_batch,
                 "max_delay_ms": args.delay_ms,
                 "queue_depth": args.queue_depth},
        "engine_config": engine_config,
    }
    # the backend child's own sinks: its serve-layer trace spans land
    # in backend.jsonl (appended across respawned generations), and an
    # orderly death dumps its flight record next to the kill reports
    env = {"PYCHEMKIN_TELEMETRY_PATH": obs.backend_jsonl,
           "PYCHEMKIN_FLIGHT_DIR": obs.dir}
    if args.chaos is not None:
        env["PYCHEMKIN_PROC_FAULTS"] = args.chaos
    sup = Supervisor(config, env_overrides=env,
                     retry_budget=args.retry_budget,
                     max_respawns=args.max_respawns,
                     default_tenant=args.tenant, recorder=rec,
                     kill_report_dir=obs.dir,
                     # the soak's health timeline: one JSONL entry per
                     # sample, replayable by chemtop --check-signals
                     health_history_path=obs.health_jsonl)
    sup.install_signal_handlers()
    print(f"# loadgen: spawning supervised backend "
          f"(chaos={'on' if args.chaos else 'off'})", file=sys.stderr)
    with sup:
        print(f"# loadgen: backend ready on port {sup.port}",
              file=sys.stderr)
        summary = loadgen.run_load(
            sup, samplers, rate_hz=args.rate, n_requests=args.n,
            rng=rng, result_timeout_s=args.timeout,
            deadline_ms=args.deadline_ms,
            trace_events=obs.trace_events,
            n_exemplars=args.exemplars, classify=classify)
        extra = {"transport": True,
                 "tenant": args.tenant,
                 "quota": args.quota,
                 "chaos": (json.loads(args.chaos)
                           if args.chaos else None),
                 "supervisor": sup.stats(),
                 # the same merged snapshot chemtop scrapes live: the
                 # backend metrics op + the supervisor's own counters
                 "metrics": sup.metrics(),
                 # the evaluated signal state + fire/clear timeline —
                 # what fired during the soak and whether it cleared
                 "health": sup.health_state()}
        try:
            extra["backend"] = sup.server_stats()
        except Exception as exc:     # noqa: BLE001 — backend may be dead
            extra["backend"] = {"error": f"{type(exc).__name__}: {exc}"}
    return summary, extra


class _HttpFleetClient:
    """The ``run_load`` duck type over the fleet HTTP ingress: each
    submit is one POST on a worker thread resolving a ServeFuture —
    the soak core cannot tell HTTP from the in-process router. Typed
    mapping back: 429 → :class:`ServerOverloaded` (counted as a
    rejection), other HTTP errors → :class:`ServerClosed`/
    :class:`ServeError` (counted, never raised out of the run)."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    def submit(self, kind, *, deadline_ms=None, trace_id=None,
               **payload):
        from pychemkin_tpu.serve.futures import ServeFuture

        fut = ServeFuture()
        body = {"kind": kind, "payload": payload, "trace": trace_id}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        threading.Thread(target=self._do, args=(fut, body),
                         daemon=True).start()
        return fut

    def _do(self, fut, body):
        import urllib.error
        import urllib.request

        from pychemkin_tpu.serve.errors import (
            ServeError, ServerClosed, ServerOverloaded)
        from pychemkin_tpu.serve.futures import ServeResult

        try:
            # sampler payloads carry numpy arrays (Y0 etc.) — encode
            # with the transport's numpy-tolerant encoder or every
            # submit dies client-side as a TypeError before the wire
            from pychemkin_tpu.serve.transport import _jsonable
            req = urllib.request.Request(
                self.base + "/v1/submit",
                data=json.dumps(_jsonable(body)).encode("utf-8"),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=600.0) as r:
                doc = json.loads(r.read().decode("utf-8"))
            fut.set_result(ServeResult(**doc["result"]))
        except urllib.error.HTTPError as exc:
            try:
                doc = json.loads(exc.read().decode("utf-8"))
            except Exception:        # noqa: BLE001 — torn error body
                doc = {}
            if exc.code == 429:
                fut.set_exception(ServerOverloaded(
                    doc.get("message", "fleet overloaded"),
                    queue_depth=int(doc.get("queue_depth", 0)),
                    retry_after_ms=doc.get("retry_after_ms")))
            else:
                fut.set_exception(ServerClosed(
                    f"HTTP {exc.code}: {doc.get('message')}"))
        except Exception as exc:     # noqa: BLE001 — typed, counted
            fut.set_exception(ServeError(
                f"{type(exc).__name__}: {exc}"))


def _run_fleet(args, kinds, bucket_sizes, rng, samplers, obs,
               classify=None):
    """The elastic-fleet soak: N supervised members behind the
    mech-aware router, the controller reconciling on their health
    signals, optionally the HTTP ingress in front. Banks per-member
    occupancy/health/compile telemetry and the controller's full
    typed action log (also as ``fleet_actions.jsonl`` in the obs dir
    — the ``run_suite --chaos`` fleet gate's artifact)."""
    from pychemkin_tpu.fleet import (FleetController, FleetIngress,
                                     FleetRouter, rendezvous_rank,
                                     route_key, shared_cache_env)

    chaos_modes = set()
    if args.chaos is not None:
        # fail fast on a typo'd spec; gray modes (a member that is
        # slow, not dead) steer the victim wiring and the post-load
        # waits below
        chaos_modes = {s.get("mode") for s in json.loads(args.chaos)}
    gray_chaos = bool(chaos_modes) and chaos_modes <= {
        "slow_replies", "stall_after_accept"}
    rec = obs.recorder
    engine_config = _surrogate_config(args, kinds, _engine_config())
    config = {
        "tenants": {args.tenant: {"mech": args.mech,
                                  "quota": args.quota}},
        "kinds": kinds,
        "chem": {"bucket_sizes": list(bucket_sizes),
                 "max_batch_size": args.max_batch,
                 "max_delay_ms": args.delay_ms,
                 "queue_depth": args.queue_depth},
        "engine_config": engine_config,
    }
    # one staging + XLA cache dir for the whole fleet: the first
    # member's warmup pays the compiles, every later spawn (scale-up,
    # replace) replays them from disk — the zero-compile-scale-up
    # contract the per-member program.compiles counters prove
    shared = shared_cache_env(os.path.join(obs.dir, "shared_cache"))
    # the chaos victim must be the member that actually RECEIVES the
    # mech's traffic — the rendezvous winner of the initial pool (the
    # controller's ensure_min ids are m0..m{N-1}) — or the injected
    # kill never fires and the soak proves nothing
    victim = (rendezvous_rank(route_key(args.mech),
                              [f"m{i}" for i in range(args.fleet)])[0]
              if args.chaos is not None else None)
    chaos_pending = [args.chaos] if args.chaos is not None else []

    def make_backend(mid):
        env = {"PYCHEMKIN_TELEMETRY_PATH": os.path.join(
                   obs.dir, f"backend_{mid}.jsonl"),
               "PYCHEMKIN_FLIGHT_DIR": obs.dir, **shared}
        max_respawns = args.max_respawns
        if chaos_pending and mid == victim:
            # the designated victim: fault injected. For KILL modes
            # the respawn budget is zeroed so its death exhausts the
            # member (typed BACKEND_LOST + router re-route) and the
            # controller's REPLACE path — not just a same-member
            # respawn — heals it. A GRAY victim keeps its budget: it
            # never dies, and the healing story is MEMBER_DEGRADED +
            # hedges + the breaker, not a replace.
            env["PYCHEMKIN_PROC_FAULTS"] = chaos_pending.pop()
            if not gray_chaos:
                max_respawns = 0
        sup = Supervisor(config, env_overrides=env,
                         retry_budget=args.retry_budget,
                         max_respawns=max_respawns,
                         default_tenant=args.tenant, recorder=rec,
                         kill_report_dir=obs.dir,
                         health_history_path=os.path.join(
                             obs.dir, f"health_{mid}.jsonl"),
                         member=mid)
        sup.start()
        print(f"# loadgen: fleet member {mid} ready on port "
              f"{sup.port}", file=sys.stderr)
        return sup

    router = FleetRouter(
        tenants={args.tenant: {"mech": args.mech,
                               "quota": args.quota}},
        recorder=rec, default_tenant=args.tenant)
    ctl = FleetController(router, make_backend,
                          min_size=args.fleet,
                          max_size=(args.fleet_max
                                    if args.fleet_max is not None
                                    else args.fleet + 1),
                          poll_s=args.fleet_poll_s, recorder=rec)
    print(f"# loadgen: spawning fleet of {args.fleet} "
          f"(chaos={'on' if args.chaos else 'off'}, "
          f"front={'http' if args.fleet_http else 'router'})",
          file=sys.stderr)
    ctl.start()
    ingress = None
    target = router
    try:
        if args.fleet_http:
            ingress = FleetIngress(router, controller=ctl,
                                   recorder=rec).start()
            target = _HttpFleetClient(
                f"http://{ingress.host}:{ingress.port}")
            print(f"# loadgen: ingress on "
                  f"http://{ingress.host}:{ingress.port}",
                  file=sys.stderr)
        summary = loadgen.run_load(
            target, samplers, rate_hz=args.rate, n_requests=args.n,
            rng=rng, result_timeout_s=args.timeout,
            deadline_ms=args.deadline_ms,
            trace_events=obs.trace_events,
            n_exemplars=args.exemplars, classify=classify)
        if gray_chaos and "slow_replies" in chaos_modes:
            # the gray story: nothing dies, so there is no replace to
            # wait for — wait instead for the cross-member detector to
            # fire on the victim and for at least one winning hedge,
            # so the banked evidence deterministically carries both
            deadline = time.time() + 30.0
            while time.time() < deadline and not (
                    any(tr["state"] == "fired"
                        for tr in router.outliers.timeline())
                    and router.stats()["hedge"]["won"] >= 1):
                time.sleep(0.2)
        elif args.chaos is not None:
            # a short ramp can outrun the poll loop: the kill lands
            # mid-load but the controller has not stepped past the
            # corpse yet — wait for the replace so the banked action
            # log deterministically carries the healing decision
            deadline = time.time() + 30.0
            while time.time() < deadline and not any(
                    a["action"] == "replace" for a in ctl.actions()):
                time.sleep(0.2)
        # spawns decided at the tail of the load run on worker threads
        # (ISSUE 19: reconciliation is asynchronous) — wait for the
        # loop to complete two more passes AND for every in-flight
        # spawn to land, so every decision made under load is in the
        # router (and the action log) before the snapshot
        settled = ctl.steps + 2
        deadline = time.time() + 60.0
        while time.time() < deadline and (
                ctl.steps < settled or ctl.state()["spawning"]):
            time.sleep(0.2)
        members = {}
        for mid in router.member_ids():
            sup = router.get(mid)
            if sup is None:
                continue
            block = {"stats": sup.stats(),
                     "health": sup.health_state()}
            try:
                m = sup.metrics()
                block["counters"] = m.get("counters")
                block["occupancy"] = (m.get("histograms") or {}).get(
                    "serve.batch_occupancy")
                block["programs"] = m.get("programs")
            except Exception as exc:  # noqa: BLE001 — dead member row
                block["metrics_error"] = (
                    f"{type(exc).__name__}: {exc}")
            members[mid] = block
        fleet_block = {
            "n": args.fleet,
            "front": "http" if args.fleet_http else "router",
            "shared_cache": shared,
            "members": members,
            "router": router.stats(),
            "controller": ctl.state(),
            "actions": ctl.actions(),
            # the gray-failure evidence (ISSUE 19): every
            # MEMBER_DEGRADED fire/clear transition with its
            # p99-vs-median ratios — alongside router.hedge /
            # router.breakers this is the acceptance artifact's proof
            # that a slow member was detected, shed, and recovered
            "degraded_timeline": router.outliers.timeline(),
            "chaos_victim": victim,
        }
    finally:
        if ingress is not None:
            ingress.close()
        router.close()               # stop the hedge scanner thread
        ctl.stop(close_members=True)
    # the controller's typed decision log, one JSONL line per action —
    # what the run_suite fleet-chaos gate replays for a replace event
    actions_path = os.path.join(obs.dir, "fleet_actions.jsonl")
    for act in fleet_block["actions"]:
        telemetry.append_jsonl(actions_path, act)
    fleet_block["actions_path"] = actions_path
    return summary, {"fleet": fleet_block, "transport": True,
                     "tenant": args.tenant, "quota": args.quota,
                     "chaos": (json.loads(args.chaos)
                               if args.chaos else None)}


def _run_flywheel(args) -> int:
    """The flywheel soak (ISSUE 20): self-contained closed loop over
    an in-process server. Trains small gen-0 surrogates on the default
    box, then offers R rounds of initially-OOD traffic
    (:func:`pychemkin_tpu.serve.loadgen.ood_mix_sampler`): round 0 is
    all verified fallback, the misses bank, the health monitor's
    per-kind ``SURROGATE_RETRAIN`` fires, the daemon retrains + rides
    the candidate in shadow on the NEXT burst, and promotion closes
    the loop — the artifact banks the per-kind hit-rate climb, the
    typed ``flywheel.*`` event trail, the zero-unverified-answers
    count, and the zero-new-compiles-after-warmup delta. A final
    scrambled-labels chaos round proves the shadow gate rejects a
    plausible-shaped but wrong candidate while the incumbent keeps
    serving."""
    from pychemkin_tpu import flywheel as fw, surrogate as sg
    from pychemkin_tpu.health.monitor import HealthMonitor

    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    sur_kinds = [k for k in kinds
                 if k.startswith(loadgen.SURROGATE_PREFIX)]
    if not sur_kinds:
        # the default --kinds is not a surrogate stream; the soak's
        # canonical pair exercises per-kind retrain scoping AND the
        # PSR-state surrogate path
        sur_kinds = ["surrogate_ignition", "surrogate_psr"]
    base_kinds = [k[len(loadgen.SURROGATE_PREFIX):] for k in sur_kinds]
    mech = load_embedded(args.mech)
    obs = _Obs(args)
    rec = obs.recorder
    work = os.path.join(obs.dir, "flywheel")
    os.makedirs(work, exist_ok=True)

    ign_cfg = _engine_config()["ignition"]
    solver_kwargs = {"ignition": ign_cfg}
    # gen-0 training boxes: the DEFAULT box per kind — except the psr
    # inlet, which must match the production sampler's cold feed
    # (T_in 300 K) or the incumbent is trained off the traffic
    # manifold from the start
    boxes = {"ignition": sg.SampleBox(),
             "equilibrium": sg.SampleBox(),
             "psr": sg.SampleBox(T=(295.0, 305.0))}
    n0 = {"ignition": 48, "equilibrium": 48, "psr": 32}

    base_shards, models = {}, {}
    for bk in base_kinds:
        path = os.path.join(work, f"base_{bk}.npz")
        print(f"# loadgen: flywheel gen-0 {bk}: labelling "
              f"{n0[bk]} draws", file=sys.stderr)
        shard, _rep = sg.generate_dataset(
            mech, bk, n=n0[bk], seed=args.seed, box=boxes[bk],
            out_path=path, solver_kwargs=solver_kwargs.get(bk))
        models[bk], _ = sg.fit_surrogate(
            shard, hidden=(16, 16), steps=200, n_members=2,
            seed=args.seed)
        base_shards[bk] = [path]

    bank = fw.MissBank(os.path.join(work, "bank"), mech, rec,
                       shard_rows=8)
    server = serve.ChemServer(
        mech, bucket_sizes=(1, 8), max_batch_size=8, max_delay_ms=5.0,
        recorder=rec, engine_config=_engine_config())
    for bk, sk in zip(base_kinds, sur_kinds):
        server.configure_engine(sk, model=models[bk],
                                base_engine=server.engine(bk),
                                bank=bank)
    print(f"# loadgen: flywheel warming {sur_kinds}", file=sys.stderr)
    warm = server.warmup(list(base_kinds) + list(sur_kinds))
    server.start()
    compiles0 = rec.counters.get("serve.compiles", 0)

    monitor = HealthMonitor(recorder=rec)
    daemon = fw.FlywheelDaemon(
        mech, monitor, bank, [server], kinds=tuple(base_kinds),
        model_dir=os.path.join(work, "models"),
        base_shards=base_shards, recorder=rec,
        train_kwargs={"steps": 200}, active_n=32,
        seed=args.seed + 5, shadow_min_n=16, promote_margin=0.0,
        solver_kwargs=solver_kwargs, base_box={"psr": boxes["psr"]})

    samplers = {sk: loadgen.ood_mix_sampler(mech, sk)
                for sk in sur_kinds}
    rng = np.random.default_rng(args.seed)
    n_burst = args.flywheel_burst
    bad_replies = 0   # ok replies missing the verified/fallback flag

    def burst(sk):
        nonlocal bad_replies
        futs = []
        for i in range(n_burst):
            kind, payload = samplers[sk](i, rng)
            futs.append(server.submit(kind, **payload))
        hits = fallbacks = 0
        for f in futs:
            r = f.result(timeout=args.timeout)
            flag = r.value.get("surrogate")
            if flag is None:
                # the no-unverified-answer contract: every ok reply is
                # either a gate-verified surrogate hit (True) or a
                # real-solver fallback (False) — a missing flag means
                # an answer escaped both
                bad_replies += 1
            elif flag:
                hits += 1
            else:
                fallbacks += 1
        return hits, fallbacks

    # synthetic clock for the health monitor: each round jumps past
    # the rule window so its ratio sees ONLY that round's deltas
    # (plus the one at-or-before-edge baseline sample)
    clock = [1.0e6]

    def observe():
        monitor.observe({"counters": dict(rec.counters)}, t=clock[0])
        clock[0] += 5.0

    rounds = []
    try:
        for r in range(args.flywheel_rounds):
            clock[0] += 400.0
            observe()                # this round's window baseline
            per_kind = {}
            for bk, sk in zip(base_kinds, sur_kinds):
                hits, falls = burst(sk)
                per_kind[bk] = {
                    "n": n_burst, "hits": hits,
                    "hit_rate": hits / n_burst, "fallbacks": falls,
                    "banked": rec.counters.get(
                        f"flywheel.banked.{bk}", 0),
                    "model_gen": server.engine(sk).model_gen}
            observe()                # the measured sample
            actions = daemon.poll()  # SURROGATE_RETRAIN -> shadow
            concluded = []
            if any(a["action"] == "retrain" for a in actions):
                for bk, sk in zip(base_kinds, sur_kinds):
                    if daemon.shadowing(bk):
                        burst(sk)    # candidate rides this in shadow
                for bk in base_kinds:
                    if daemon.shadowing(bk):
                        s = daemon.finish_round(bk)
                        if s is not None:
                            concluded.append(
                                {"kind": bk,
                                 "verdict": s["verdict"],
                                 "model_gen": s["model_gen"]})
            rounds.append({"round": r, "kinds": per_kind,
                           "actions": actions,
                           "concluded": concluded})
            print("# loadgen: flywheel round %d: %s (promotions %d)"
                  % (r, ", ".join(
                      f"{bk} {per_kind[bk]['hits']}/{n_burst}"
                      for bk in base_kinds),
                     rec.counters.get("flywheel.promoted", 0)),
                  file=sys.stderr)

        # chaos round: a scrambled-labels candidate against the now-
        # strong incumbent — the shadow verdict must reject it and the
        # incumbent must keep serving
        scramble = None
        promoted = [e.get("req_kind")
                    for e in rec.events("flywheel.promoted")]
        if promoted:
            bk = promoted[0]
            sk = loadgen.SURROGATE_PREFIX + bk
            gen_before = server.engine(sk).model_gen
            print(f"# loadgen: flywheel chaos: scrambled {bk} "
                  "candidate", file=sys.stderr)
            daemon.start_round(bk, scramble=True)
            burst(sk)
            s = daemon.finish_round(bk)
            scramble = {
                "kind": bk,
                "verdict": s["verdict"] if s else "undecided",
                "model_gen_before": gen_before,
                "model_gen_after": server.engine(sk).model_gen,
                "incumbent_kept":
                    server.engine(sk).model_gen == gen_before}

        compiles1 = rec.counters.get("serve.compiles", 0)
        fw_state = server.flywheel_state()
    finally:
        server.close()

    r0 = rounds[0]["kinds"]
    rN = rounds[-1]["kinds"]
    artifact = {
        "tool": "loadgen",
        "mode": "flywheel",
        "mech": args.mech,
        "kinds": sur_kinds,
        "seed": args.seed,
        "rounds_requested": args.flywheel_rounds,
        "burst": n_burst,
        "ood_mix": {"T": list(loadgen.OOD_MIX_T),
                    "eq_T": list(loadgen.OOD_MIX_EQ_T),
                    "tau": list(loadgen.OOD_MIX_TAU)},
        "rounds": rounds,
        "scramble": scramble,
        "promotions": rec.counters.get("flywheel.promoted", 0),
        "rejections": rec.counters.get("flywheel.rejected", 0),
        "hit_rate_round0": {bk: r0[bk]["hit_rate"]
                            for bk in base_kinds},
        "hit_rate_final": {bk: rN[bk]["hit_rate"]
                           for bk in base_kinds},
        "model_gen": fw_state["model_gen"],
        "banked": {bk: rec.counters.get(f"flywheel.banked.{bk}", 0)
                   for bk in base_kinds},
        "unverified_answers": bad_replies,
        "warmup_compiles": warm,
        "compiles_after_warmup": compiles1 - compiles0,
        "flywheel_events": [e for e in rec.events()
                            if str(e.get("kind", "")
                                   ).startswith("flywheel.")],
        "flywheel_state": fw_state,
        "telemetry": rec.snapshot(),
        **obs.artifacts(),
    }
    telemetry.atomic_write_json(args.out, artifact)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k not in ("telemetry", "rounds",
                                   "flywheel_events")}),
          flush=True)
    print(f"# loadgen: flywheel artifact banked to {args.out}",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.flywheel_rounds:
        return _run_flywheel(args)
    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    bucket_sizes = tuple(int(b) for b in args.buckets.split(","))

    mech = load_embedded(args.mech)
    rng = np.random.default_rng(args.seed)
    classify = None
    stiffness_mix = None
    if args.stiffness_mix:
        ign_kinds = [k for k in kinds
                     if (k[len(loadgen.SURROGATE_PREFIX):]
                         if k.startswith(loadgen.SURROGATE_PREFIX)
                         else k) == "ignition"]
        if not ign_kinds:
            raise SystemExit("--stiffness-mix needs an ignition-"
                             "family kind in --kinds")
        samplers = loadgen.default_samplers(
            mech, [k for k in kinds if k not in ign_kinds])
        for k in ign_kinds:
            mix, classify = loadgen.stiffness_mix_sampler(mech, k)
            samplers.append(mix)
        stiffness_mix = {"T_range": list(loadgen.STIFFNESS_MIX_T),
                         "phi_range": list(loadgen.STIFFNESS_MIX_PHI),
                         "kinds": ign_kinds}
    elif args.ood_mix:
        sur_kinds = [k for k in kinds
                     if k.startswith(loadgen.SURROGATE_PREFIX)]
        if not sur_kinds:
            raise SystemExit("--ood-mix needs a surrogate_* kind in "
                             "--kinds")
        samplers = loadgen.default_samplers(
            mech, [k for k in kinds if k not in sur_kinds])
        samplers.extend(loadgen.ood_mix_sampler(mech, k)
                        for k in sur_kinds)
    else:
        samplers = loadgen.default_samplers(mech, kinds)
    obs = _Obs(args)

    if args.fleet is not None:
        runner = _run_fleet
    elif args.transport:
        runner = _run_transport
    else:
        runner = _run_inprocess
    summary, extra = runner(args, kinds, bucket_sizes, rng, samplers,
                            obs, classify)
    if stiffness_mix is not None:
        extra["stiffness_mix"] = stiffness_mix
    extra.update(obs.artifacts())

    artifact = {
        "tool": "loadgen",
        "mech": args.mech,
        "kinds": kinds,
        "seed": args.seed,
        "buckets": list(bucket_sizes),
        "max_batch_size": args.max_batch,
        "max_delay_ms": args.delay_ms,
        "deadline_ms": args.deadline_ms,
        **summary,
        **extra,
    }
    telemetry.atomic_write_json(args.out, artifact)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k not in ("telemetry", "metrics")}),
          flush=True)
    print(f"# loadgen: artifact banked to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
