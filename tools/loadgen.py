#!/usr/bin/env python
"""Open-loop Poisson load generator for the online serving layer.

Drives an in-process :class:`pychemkin_tpu.serve.ChemServer` with a
seeded Poisson request stream (open loop: arrivals keep their schedule
regardless of completions, so queueing collapse is visible instead of
self-throttled away) and banks a JSON latency artifact with the same
atomic tmp+rename idiom as the bench (a kill mid-run leaves either the
previous artifact or a complete new one, never a torn file).

Usage::

    python tools/loadgen.py --mech h2o2 --kinds equilibrium,ignition \
        --rate 100 --n 200 --seed 0 --out LOADGEN.json

The artifact carries the request-side latency distribution
(p50/p95/p99/mean/max ms), occupancy, rejection and rescue counts,
plus the server-side telemetry snapshot (queue-depth gauge,
wait/solve/occupancy histograms, per-status counters).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

# runnable as a script from anywhere: the repo root is the package's
# parent, same bootstrap as bench.py
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pychemkin_tpu import serve, telemetry          # noqa: E402
from pychemkin_tpu.mechanism import load_embedded   # noqa: E402
from pychemkin_tpu.serve import loadgen             # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   help="embedded mechanism name (default h2o2)")
    p.add_argument("--kinds", default="equilibrium",
                   help="comma list of request kinds "
                        "(ignition,psr,equilibrium)")
    p.add_argument("--rate", type=float, default=100.0,
                   help="offered arrival rate, requests/s")
    p.add_argument("--n", type=int, default=200,
                   help="number of arrivals to offer")
    p.add_argument("--seed", type=int, default=0,
                   help="rng seed (arrival schedule + payloads)")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--delay-ms", type=float, default=2.0)
    p.add_argument("--buckets", default="1,8,32",
                   help="comma list of bucket sizes")
    p.add_argument("--queue-depth", type=int, default=256)
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-future result timeout, s")
    p.add_argument("--out", default="LOADGEN.json",
                   help="artifact path (atomic rewrite)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    bucket_sizes = tuple(int(b) for b in args.buckets.split(","))

    mech = load_embedded(args.mech)
    rec = telemetry.MetricsRecorder()
    server = serve.ChemServer(
        mech, bucket_sizes=bucket_sizes, max_batch_size=args.max_batch,
        max_delay_ms=args.delay_ms, queue_depth=args.queue_depth,
        recorder=rec,
        engine_config={"ignition": {"rtol": 1e-6, "atol": 1e-10,
                                    "max_steps_per_segment": 4000}})
    rng = np.random.default_rng(args.seed)
    samplers = loadgen.default_samplers(mech, kinds)

    print(f"# loadgen: warming {kinds} over buckets {bucket_sizes}",
          file=sys.stderr)
    warm = server.warmup(kinds)
    with server:
        summary = loadgen.run_load(
            server, samplers, rate_hz=args.rate, n_requests=args.n,
            rng=rng, result_timeout_s=args.timeout)

    artifact = {
        "tool": "loadgen",
        "mech": args.mech,
        "kinds": kinds,
        "seed": args.seed,
        "buckets": list(bucket_sizes),
        "max_batch_size": args.max_batch,
        "max_delay_ms": args.delay_ms,
        "warmup_compiles": warm,
        **summary,
        "telemetry": rec.snapshot(),
    }
    telemetry.atomic_write_json(args.out, artifact)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k != "telemetry"}), flush=True)
    print(f"# loadgen: artifact banked to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
