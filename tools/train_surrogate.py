#!/usr/bin/env python
"""train_surrogate — dataset generation + ensemble training CLI for
the neural surrogate fast path (``pychemkin_tpu/surrogate/``).

Two stages, each skippable:

1. **Label** — sample a (T, P, phi) box and run the REAL solver over
   it under the durable sweep driver: generation is checkpointed
   (``<shard>.ck.npz``), SIGKILL/SIGTERM-resumable (rc 75), and banks
   a signed npz shard. Pass ``--shards`` to reuse/concatenate
   previously banked shards instead (the flywheel: every sweep adds
   training data) — their problem signatures are verified against the
   current mechanism so a stale shard can never silently train
   against different chemistry.
2. **Fit** — train an MLP ensemble (plain-pytree params, handwritten
   Adam), save the self-contained model npz (normalization,
   trained-domain box, signatures ride inside), and bank a
   training-curve artifact (atomic JSON) next to it.

Usage::

    python tools/train_surrogate.py --mech h2o2 --kind ignition \
        --n 512 --seed 0 --out IGN_SURROGATE.npz
    python tools/train_surrogate.py --mech h2o2 --kind ignition \
        --shards shard_a.npz,shard_b.npz --out IGN_SURROGATE.npz

Serve the result::

    server.configure_engine("surrogate_ignition",
                            model_path="IGN_SURROGATE.npz",
                            base_engine=server.engine("ignition"))
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as a script from anywhere (same bootstrap as bench.py)
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pychemkin_tpu import surrogate, telemetry        # noqa: E402
from pychemkin_tpu.mechanism import load_embedded     # noqa: E402
from pychemkin_tpu.resilience.driver import JobInterrupted  # noqa: E402


def _range(text: str):
    lo, hi = (float(x) for x in text.split(","))
    return (lo, hi)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   help="embedded mechanism name (default h2o2)")
    p.add_argument("--kind", default="ignition",
                   choices=list(surrogate.dataset.KINDS))
    # -- dataset box ----------------------------------------------------
    p.add_argument("--n", type=int, default=512,
                   help="conditions to sample and label")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--T-range", type=_range, default=(1250.0, 1400.0),
                   metavar="LO,HI", help="temperature box, K")
    p.add_argument("--P-range", type=_range, default=(0.9e6, 1.2e6),
                   metavar="LO,HI", help="pressure box, dyne/cm^2")
    p.add_argument("--phi-range", type=_range, default=(0.85, 1.15),
                   metavar="LO,HI", help="equivalence-ratio box")
    p.add_argument("--t-end", type=float, default=4e-4,
                   help="ignition integration horizon, s")
    p.add_argument("--chunk", type=int, default=64,
                   help="labeling sweep chunk size (driver banking "
                        "cadence)")
    p.add_argument("--shard-out", default=None,
                   help="bank the labeled shard here (default: "
                        "<out stem>_shard.npz)")
    p.add_argument("--shards", default=None,
                   help="comma list of EXISTING shards to train on "
                        "instead of generating")
    # -- training -------------------------------------------------------
    p.add_argument("--hidden", default="32,32",
                   help="comma list of hidden-layer widths")
    p.add_argument("--steps", type=int, default=1500,
                   help="Adam steps per ensemble member")
    p.add_argument("--members", type=int, default=3,
                   help="ensemble size (disagreement = trust signal)")
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--train-seed", type=int, default=0)
    p.add_argument("--out", default="SURROGATE.npz",
                   help="model npz path (atomic rewrite)")
    p.add_argument("--curve-out", default=None,
                   help="training-curve JSON artifact (default: "
                        "<out stem>_curve.json)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    mech = load_embedded(args.mech)
    stem = os.path.splitext(args.out)[0]
    t0 = time.time()

    if args.shards:
        paths = [s for s in args.shards.split(",") if s.strip()]
        print(f"# train_surrogate: loading {len(paths)} shard(s)",
              file=sys.stderr)
        data = surrogate.load_shards(
            paths, expect_mech_sig=surrogate.mech_signature(mech))
    else:
        box = surrogate.SampleBox(T=args.T_range, P=args.P_range,
                                  phi=args.phi_range, t_end=args.t_end)
        shard_out = args.shard_out or f"{stem}_shard.npz"
        job_report: dict = {}
        print(f"# train_surrogate: labeling {args.n} {args.kind} "
              f"conditions (checkpointed at {shard_out}.ck.npz)",
              file=sys.stderr)
        try:
            data, report = surrogate.generate_dataset(
                mech, args.kind, n=args.n, seed=args.seed, box=box,
                out_path=shard_out, chunk_size=args.chunk,
                job_report=job_report)
        except JobInterrupted as e:
            # the documented resumable contract: rerun the same
            # command to resume labeling after the banked chunk
            print(f"# train_surrogate: interrupted — {e}",
                  file=sys.stderr)
            return e.rc
        print(f"# train_surrogate: labeled "
              f"{int(data['valid'].sum())}/{args.n} valid "
              f"(resume_count={report.resume_count})", file=sys.stderr)

    hidden = [int(h) for h in args.hidden.split(",") if h.strip()]
    model, curves = surrogate.fit_surrogate(
        data, hidden=hidden, steps=args.steps, lr=args.lr,
        n_members=args.members, seed=args.train_seed)
    surrogate.save_model(args.out, model)

    artifact = surrogate.training_curve_artifact(
        model, curves, wall_s=time.time() - t0)
    curve_out = args.curve_out or f"{stem}_curve.json"
    telemetry.atomic_write_json(curve_out, artifact)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k != "curves"}), flush=True)
    print(f"# train_surrogate: model -> {args.out}; curves -> "
          f"{curve_out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
