#!/usr/bin/env python
"""compile_audit — the post-warmup recompile gate.

The serving contract since ISSUE 9 is "live traffic never pays a
compile": :meth:`ChemServer.warmup` traces the whole bucket ladder up
front, adaptive scheduling only picks warmed rungs, and the scheduled
sweep's per-rung programs compile once per width. The program
observatory (``pychemkin_tpu/obs``) finally makes that contract
CHECKABLE from the outside — every compile increments the
``program.compiles`` counter family with a content-addressed program
id — and this tool turns it into a CI gate:

1. build one in-process ``ChemServer`` (h2o2 by default) and
   ``warmup()`` its engines; run one scheduled compacted ignition
   sweep (the sweep's first pass through each ladder rung IS its
   warmup — there is no separate warm phase for sweeps);
2. snapshot the per-program compile counters;
3. serve a mixed-kind soak (ignition + equilibrium across buckets) and
   repeat the SAME sweep;
4. diff: any ``program.compiles`` growth after step 2 means a live
   dispatch paid trace+build wall — rc 1, naming the offending
   program ids and their configs (the diff is the debugging payload:
   a knob flipped mid-run shows up as a new program id whose config
   differs in exactly the flipped field).

The same run feeds both phases' counter snapshots through the health
rule engine and reports whether ``COMPILE_STORM`` fired — the gate
and the pager alert are exercised by the same evidence.

``--perturb`` (or ``PYCHEMKIN_COMPILE_AUDIT_PERTURB=1`` in the env —
how ``run_suite --compile-audit`` drives the negative twin) flips
``PYCHEMKIN_SOLVE_PROFILE`` between the phases: a trace-time knob the
jit caches do not key on, so every engine re-traces on its next
dispatch. The perturbed twin MUST fail rc 1 and fire COMPILE_STORM;
the unperturbed run must stay green. A gate that cannot fail is not a
gate.

Usage::

    python tools/compile_audit.py --mech h2o2 --out COMPILE_AUDIT.json
    PYCHEMKIN_COMPILE_AUDIT_PERTURB=1 python tools/compile_audit.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                         # noqa: E402

from pychemkin_tpu import health, schedule, serve, telemetry  # noqa: E402
from pychemkin_tpu.mechanism import load_embedded          # noqa: E402
from pychemkin_tpu.obs import programs as obs_programs     # noqa: E402
from pychemkin_tpu.serve import loadgen                    # noqa: E402

P_ATM = 1.01325e6
PERTURB_ENV = "PYCHEMKIN_COMPILE_AUDIT_PERTURB"


def _compile_counters(rec) -> dict:
    """The ``program.compiles*`` family from one recorder — the whole
    audit diffs exactly what the schema exports, nothing bespoke."""
    return {k: int(v) for k, v in rec.counters.items()
            if k.startswith("program.compiles")}


def _sample(rec) -> dict:
    """One health-ring sample from the live recorder: the same
    normalize path a chemtop scrape takes, so COMPILE_STORM sees the
    same evidence here as it would on a real fleet."""
    return health.normalize_sample({
        "counters": dict(rec.counters),
        "histogram_states": {},
        "pid": os.getpid(),
        "uptime_s": 0.0,
    })


def _run_sweep(mech, B: int, rec) -> None:
    Y0 = loadgen.stoich_h2_air_Y(mech)
    T0s = np.linspace(1000.0, 1400.0, B)
    schedule.compacted_ignition_sweep(
        mech, "CONP", "ENRG", T0s,
        np.full(B, P_ATM), np.tile(Y0, (B, 1)),
        np.full(B, 2e-5), rtol=1e-6, atol=1e-9,
        round_len=64, recorder=rec, label="compile_audit")


def _soak(server, Y0, n: int) -> None:
    futs = []
    for i in range(n):
        if i % 2 == 0:
            futs.append(server.submit_ignition(
                T0=1100.0 + 25.0 * i, P0=P_ATM, Y0=Y0, t_end=2e-5))
        else:
            futs.append(server.submit_equilibrium(
                T=1200.0 + 10.0 * i, P=P_ATM, Y=Y0, option=1))
    for f in futs:
        f.result(timeout=300)


def run_audit(mech_name: str, n_requests: int, sweep_B: int,
              perturb: bool) -> dict:
    mech = load_embedded(mech_name)
    rec = telemetry.get_recorder()
    obs_programs.reset_registry()
    Y0 = loadgen.stoich_h2_air_Y(mech)

    server = serve.ChemServer(mech, bucket_sizes=(1, 4, 8),
                              max_delay_ms=1.0, recorder=rec,
                              kinds=("ignition", "equilibrium")).start()
    try:
        # phase W: everything tier-1 traffic will touch gets compiled
        # here — the serve ladder via warmup(), the sweep rungs via a
        # first full pass
        server.warmup()
        _run_sweep(mech, sweep_B, rec)
        warm = _compile_counters(rec)
        ring = health.SnapshotRing(cap=8)
        engine = health.HealthEngine(
            recorder=telemetry.MetricsRecorder())
        ring.append(_sample(rec))
        engine.evaluate(ring)

        if perturb:
            # the negative twin: flip a trace-time knob the jit caches
            # do not key on — every engine re-traces on next dispatch
            cur = os.environ.get("PYCHEMKIN_SOLVE_PROFILE")
            os.environ["PYCHEMKIN_SOLVE_PROFILE"] = \
                "" if cur in ("1", "true") else "1"

        # phase L: live mixed-kind soak + the SAME sweep again
        _soak(server, Y0, n_requests)
        _run_sweep(mech, sweep_B, rec)

        live = _compile_counters(rec)
        ring.append(_sample(rec))
        signals = engine.evaluate(ring)
    finally:
        server.close()

    new = {k: live.get(k, 0) - warm.get(k, 0)
           for k in live if live.get(k, 0) > warm.get(k, 0)}
    # name the offending programs: the registry still holds the full
    # config of every id, so the report says WHAT recompiled, not just
    # that something did
    state = obs_programs.get_registry().programs_state()["by_id"]
    offenders = {
        pid.split("program.compiles.", 1)[-1]: state.get(
            pid.split("program.compiles.", 1)[-1], {})
        for pid in new if pid != "program.compiles"}
    storm = next((s for s in signals
                  if s["signal"] == "COMPILE_STORM"), None)
    rc = 1 if new else 0
    return {
        "tool": "compile_audit",
        "t": time.time(),
        "mech": mech_name,
        "perturb": perturb,
        "n_requests": n_requests,
        "sweep_B": sweep_B,
        "warm_compiles": warm,
        "live_compiles": live,
        "new_compiles": new,
        "offenders": offenders,
        "compile_storm": {
            "state": storm["state"] if storm else None,
            "evidence": (storm.get("evidence") if storm else None),
        },
        "rc": rc,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   choices=["h2o2", "grisyn"])
    p.add_argument("--requests", type=int, default=12,
                   help="mixed-kind soak size in phase L")
    p.add_argument("--sweep-batch", type=int, default=96,
                   help="scheduled-sweep width (both phases)")
    p.add_argument("--perturb", action="store_true",
                   help="flip PYCHEMKIN_SOLVE_PROFILE between phases "
                        f"(also via {PERTURB_ENV}=1) — the audit MUST "
                        "then fail")
    p.add_argument("--out", default=None,
                   help="bank the verdict JSON here (atomic)")
    args = p.parse_args(argv)
    perturb = args.perturb or bool(os.environ.get(PERTURB_ENV))

    out = run_audit(args.mech, args.requests, args.sweep_batch,
                    perturb)
    if args.out:
        telemetry.atomic_write_json(args.out, out)
    print(json.dumps(out))
    if out["rc"]:
        print("# compile_audit: POST-WARMUP COMPILES: "
              + ", ".join(sorted(out["new_compiles"])),
              file=sys.stderr)
    return out["rc"]


if __name__ == "__main__":
    sys.exit(main())
