"""Generate the stored numeric baselines in tests/baseline/.

Reproduces the reference's oracle strategy (SURVEY.md §4: 26 stored
.baseline vectors diffed by the test harness) in the only honest form
available without the licensed Chemkin library:

- INDEPENDENT-PATH baselines (generator: scipy) — the workload is
  re-solved by a different integrator/solver (scipy BDF / LSODA /
  fsolve) sharing only the kinetics/thermo kernels, so the framework's
  own solvers (SDIRK3, PSR Newton) are genuinely cross-checked;
- REGRESSION baselines (generator: regression) — workloads with no
  independent numerical path here (flame eigenvalue, engines,
  equilibrium); the stored vector pins today's validated answer, and
  the consuming test ALSO anchors the headline number to literature
  where one exists (T_ad, CJ speed, flame speed).

Each file records its generator + date under non-compared keys.

Run from repo root:  python tools/gen_baselines.py  [--only name]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pychemkin_tpu.constants import P_ATM, R_GAS  # noqa: E402
from pychemkin_tpu.mechanism import load_embedded  # noqa: E402
from pychemkin_tpu.ops import kinetics, reactors, thermo  # noqa: E402
from pychemkin_tpu.utils import baseline as bl  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "baseline")

MAJORS = ["H2", "O2", "H2O", "OH", "N2"]


def _mech():
    return load_embedded("h2o2")


def _stoich_Y(mech):
    names = list(mech.species_names)
    X = np.zeros(len(names))
    X[names.index("H2")] = 2.0
    X[names.index("O2")] = 1.0
    X[names.index("N2")] = 3.76
    return np.asarray(thermo.X_to_Y(mech, jnp.asarray(X / X.sum())))


def _write(name, data, generator):
    data = {"generator": [generator], **data}
    path = os.path.join(OUT, name + ".baseline")
    bl.write_result(path, data)
    print("wrote", path)


# ---------------------------------------------------------------------------
# independent-path baselines (scipy)

def gen_conv_batch():
    """CONV/ENRG endpoint state by scipy BDF (independent integrator).

    Constant-volume adiabatic: rho constant; dY/dt = wdot W / rho,
    du/dt = 0 => cv dT/dt = -sum u_k(molar) wdot_k / rho."""
    from scipy.integrate import solve_ivp

    mech = _mech()
    Y0 = _stoich_Y(mech)
    T0, P0, t_end = 1150.0, P_ATM, 2e-3
    rho = float(thermo.density(mech, T0, P0, jnp.asarray(Y0)))

    def rhs(t, y):
        Y = np.clip(y[:-1], 0.0, 1.0)
        T = y[-1]
        C = thermo.Y_to_C(mech, jnp.asarray(Y), rho)
        wbar = float(thermo.mean_molecular_weight_Y(mech, jnp.asarray(Y)))
        P = rho * R_GAS * T / wbar
        wdot = np.asarray(kinetics.net_production_rates(
            mech, T, C, P))
        dY = wdot * np.asarray(mech.wt) / rho
        u_molar = np.asarray(thermo.h_RT(mech, T)) * R_GAS * T - R_GAS * T
        cv = float(thermo.mixture_cp_mass(mech, T, jnp.asarray(Y))) - \
            R_GAS / wbar
        dT = -float(u_molar @ wdot) / (rho * cv)
        return np.concatenate([dY, [dT]])

    sol = solve_ivp(rhs, (0.0, t_end), np.concatenate([Y0, [T0]]),
                    method="BDF", rtol=1e-9, atol=1e-14)
    assert sol.success
    Yf, Tf = sol.y[:-1, -1], float(sol.y[-1, -1])
    wbar = float(thermo.mean_molecular_weight_Y(mech, jnp.asarray(
        np.clip(Yf, 0, 1))))
    Pf = rho * R_GAS * Tf / wbar
    names = list(mech.species_names)
    data = {
        "tolerance-var": [1e-6, 0.005],
        "tolerance-frac": [1e-6, 0.01],
        "state-temperature": [Tf],
        "state-pressure": [Pf],
    }
    for s in MAJORS:
        data[f"species-{s}"] = [float(Yf[names.index(s)])]
    _write("conv_batch", data, "scipy-BDF rtol1e-9")


def gen_pfr_exit():
    """PFR (ENRG, momentum off) exit state by scipy LSODA marching."""
    from scipy.integrate import solve_ivp

    mech = _mech()
    Y0 = _stoich_Y(mech)
    T0, P0, mdot, A, L = 1100.0, P_ATM, 2.0, 1.0, 30.0

    def rhs(x, y):
        Y = np.clip(y[:-1], 0.0, 1.0)
        T = y[-1]
        rho = float(thermo.density(mech, T, P0, jnp.asarray(Y)))
        u = mdot / (rho * A)
        C = thermo.Y_to_C(mech, jnp.asarray(Y), rho)
        wdot = np.asarray(kinetics.net_production_rates(mech, T, C, P0))
        dY = wdot * np.asarray(mech.wt) / (rho * u)
        h_molar = np.asarray(thermo.h_RT(mech, T)) * R_GAS * T
        cp = float(thermo.mixture_cp_mass(mech, T, jnp.asarray(Y)))
        dT = -float(h_molar @ wdot) / (rho * u * cp)
        return np.concatenate([dY, [dT]])

    sol = solve_ivp(rhs, (0.0, L), np.concatenate([Y0, [T0]]),
                    method="LSODA", rtol=1e-10, atol=1e-14)
    assert sol.success
    Yf, Tf = np.clip(sol.y[:-1, -1], 0, 1), float(sol.y[-1, -1])
    rho_f = float(thermo.density(mech, Tf, P0, jnp.asarray(Yf)))
    u_f = mdot / (rho_f * A)
    names = list(mech.species_names)
    data = {
        "tolerance-var": [1e-6, 0.005],
        "tolerance-frac": [1e-6, 0.01],
        "state-temperature": [Tf],
        "state-velocity": [u_f],
    }
    for s in MAJORS:
        data[f"species-{s}"] = [float(Yf[names.index(s)])]
    _write("pfr_exit", data, "scipy-LSODA rtol1e-10")


def gen_psr_scurve():
    """Burning-branch PSR exit temperatures over a residence-time
    ladder, by INDEPENDENT-path transient-CSTR integration: scipy BDF
    marches the open-reactor ODEs

        dY/dt = (Y_in - Y)/tau + wdot W / rho
        dh/dt = (h_in - h)/tau  =>  cp dT/dt = (h_in-h)/tau - sum h_k dY_k/dt

    to steady state (t = 60 tau from the hot equilibrium state). The
    framework's damped-Newton PSR must land on the same burning branch."""
    from scipy.integrate import solve_ivp

    from pychemkin_tpu.ops import equilibrium as eq_ops

    mech = _mech()
    Y_in = _stoich_Y(mech)
    T_in, P = 298.15, P_ATM
    h_in = float(thermo.mixture_enthalpy_mass(mech, T_in,
                                              jnp.asarray(Y_in)))
    g = eq_ops.equilibrate(mech, T_in, P, jnp.asarray(Y_in), option=5)
    z_eq = np.concatenate([np.asarray(g.Y), [float(g.T)]])
    taus = [1e-1, 1e-2, 1e-3]
    T_out = []
    for tau in taus:
        def rhs(t, zz, tau=tau):
            Y = np.clip(zz[:-1], 0.0, 1.0)
            T = zz[-1]
            rho = float(thermo.density(mech, T, P, jnp.asarray(Y)))
            C = thermo.Y_to_C(mech, jnp.asarray(Y), rho)
            wdot = np.asarray(kinetics.net_production_rates(
                mech, T, C, P))
            dY = (Y_in - zz[:-1]) / tau + wdot * np.asarray(
                mech.wt) / rho
            h = float(thermo.mixture_enthalpy_mass(mech, T,
                                                   jnp.asarray(Y)))
            cp = float(thermo.mixture_cp_mass(mech, T, jnp.asarray(Y)))
            h_k = np.asarray(thermo.species_enthalpy_mass(mech, T))
            dT = ((h_in - h) / tau - float(h_k @ dY)) / cp
            return np.concatenate([dY, [dT]])

        sol = solve_ivp(rhs, (0.0, 60.0 * tau), z_eq, method="BDF",
                        rtol=1e-10, atol=1e-14)
        assert sol.success, (tau, sol.message)
        z = sol.y[:, -1]
        # confirm steadiness: the state must have stopped moving
        drift = np.abs(rhs(0.0, z))
        assert drift[-1] < 1e-4 and np.max(drift[:-1]) < 1e-6, (
            tau, drift[-1], np.max(drift[:-1]))
        T_out.append(float(z[-1]))
    data = {
        "tolerance-var": [1e-6, 0.005],
        "state-residence_time": taus,
        "state-exit_temperature": T_out,
    }
    _write("psr_scurve", data,
           "scipy-BDF transient CSTR marched to steady state")


# ---------------------------------------------------------------------------
# regression baselines (framework-generated, literature-anchored in tests)

def gen_equilibrium_composition():
    import pychemkin_tpu as ck

    mech = _mech()
    chem = ck.Chemistry.from_mechanism(mech)
    mix = ck.Mixture(chem)
    mix.temperature = 298.15
    mix.pressure = P_ATM
    mix.X = {"H2": 2.0, "O2": 1.0, "N2": 3.76}
    eqm = ck.equilibrium(mix, opt=5)       # HP: adiabatic flame
    names = list(mech.species_names)
    X = np.asarray(eqm.X)
    data = {
        "tolerance-var": [1e-6, 1e-4],
        "tolerance-frac": [1e-6, 1e-3],
        "state-temperature": [float(eqm.temperature)],
    }
    for s in MAJORS + ["H", "O"]:
        data[f"species-{s}"] = [float(X[names.index(s)])]
    _write("equilibrium_composition", data,
           "regression (element-potential Newton); T_ad anchored to "
           "literature in test")


def gen_cj_detonation():
    import pychemkin_tpu as ck

    mech = _mech()
    chem = ck.Chemistry.from_mechanism(mech)
    mix = ck.Mixture(chem)
    mix.temperature = 298.15
    mix.pressure = P_ATM
    mix.X = {"H2": 2.0, "O2": 1.0, "N2": 3.76}
    speeds, burnt = ck.detonation(mix)
    data = {
        "tolerance-var": [1e-6, 1e-4],
        "state-sound_speed": [float(speeds[0])],
        "state-detonation_speed": [float(speeds[1])],
        "state-burnt_temperature": [float(burnt.temperature)],
        "state-burnt_pressure": [float(burnt.pressure)],
    }
    _write("cj_detonation", data,
           "regression (CJ equilibrium solve); speed anchored to "
           "literature in test")


def gen_flame_speed():
    from pychemkin_tpu.ops import flame1d

    mech = _mech()
    Y0 = _stoich_Y(mech)
    sol = flame1d.solve_flame(mech, P=P_ATM, T_in=298.0, Y_in=Y0,
                              x_start=0.0, x_end=2.0)
    assert sol.converged
    data = {
        "tolerance-var": [1e-6, 2e-3],
        "state-flame_speed": [float(sol.flame_speed)],
        "state-max_temperature": [float(np.max(sol.T))],
    }
    _write("flame_speed", data,
           "regression (PREMIX-class eigenvalue solve); Su anchored "
           "to literature in test")


def _engine_mix():
    import pychemkin_tpu as ck

    mech = _mech()
    chem = ck.Chemistry.from_mechanism(mech)
    m = ck.Mixture(chem)
    m.temperature = 420.0
    m.pressure = P_ATM
    m.X = {"H2": 2.0, "O2": 1.0, "N2": 3.76 * 2}   # lean-ish charge
    return m


def _set_geometry(e):
    e.bore = 8.0
    e.stroke = 9.0
    e.connecting_rod_length = 15.0
    e.compression_ratio = 16.0
    e.RPM = 1500.0
    e.starting_CA = -142.0
    e.ending_CA = 116.0


def gen_hcci_ca50():
    from pychemkin_tpu.models import HCCIengine

    e = HCCIengine(_engine_mix())
    _set_geometry(e)
    assert e.run() == 0
    ca10, ca50, ca90 = e.get_engine_heat_release_CAs()
    avg = e.process_average_engine_solution()
    data = {
        "tolerance-var": [1e-6, 1e-3],
        "state-CA10": [float(ca10)],
        "state-CA50": [float(ca50)],
        "state-CA90": [float(ca90)],
        "state-peak_pressure_atm": [float(np.max(avg["pressure"]) /
                                          P_ATM)],
    }
    _write("hcci_ca50", data, "regression (slider-crank HCCI solve)")


def gen_si_heat_release():
    from pychemkin_tpu.models import SIengine

    si = SIengine(_engine_mix())
    _set_geometry(si)
    si.compression_ratio = 9.5
    si.RPM = 2000.0
    si.wiebe_parameters(2.0, 5.0)
    si.set_burn_timing(-10.0, 40.0)
    si.define_product_composition(["H2O", "N2"])
    assert si.run() == 0
    ca10, ca50, ca90 = si.get_engine_heat_release_CAs()
    avg = si.process_average_engine_solution()
    data = {
        "tolerance-var": [1e-6, 1e-3],
        "state-CA10": [float(ca10)],
        "state-CA50": [float(ca50)],
        "state-CA90": [float(ca90)],
        "state-peak_pressure_atm": [float(np.max(avg["pressure"]) /
                                          P_ATM)],
    }
    _write("si_heat_release", data, "regression (Wiebe-burn SI solve)")


GENERATORS = {
    "conv_batch": gen_conv_batch,
    "pfr_exit": gen_pfr_exit,
    "psr_scurve": gen_psr_scurve,
    "equilibrium_composition": gen_equilibrium_composition,
    "cj_detonation": gen_cj_detonation,
    "flame_speed": gen_flame_speed,
    "hcci_ca50": gen_hcci_ca50,
    "si_heat_release": gen_si_heat_release,
}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None)
    args = ap.parse_args()
    for name, fn in GENERATORS.items():
        if args.only and name != args.only:
            continue
        fn()
