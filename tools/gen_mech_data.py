"""Generate the embedded CHEMKIN-format mechanism fixtures.

The reference ships no mechanism files (they live in the licensed Ansys
install), so the rebuild embeds its own fixtures:

- ``h2o2.inp`` / ``therm_h2o2.dat`` / ``tran_h2o2.dat`` — a GRI-3.0-derived
  H2/O2/N2/AR subsystem (10 species, 26 reactions) exercising third bodies,
  Troe falloff, duplicates, and negative activation energies.
- ``grisyn.inp`` — a synthetic GRI-3.0-*sized* mechanism (53 species /
  325 reactions) for performance benchmarking: same tensor shapes and
  reaction-type mix as GRI-3.0, thermodynamically consistent by
  construction, but NOT a validated chemistry model.

NASA-7 a6/a7 of the high-T range are repaired to enforce exact h/s
continuity at Tmid, guarding against transcription error.

Run from repo root:  python tools/gen_mech_data.py
"""

import os

import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "..",
                   "pychemkin_tpu", "mechanism", "data")

# species: (composition, Tlow, Tmid, Thigh, low7, high7)
# NASA-7 polynomials (GRI-3.0 thermo database values).
THERMO = {
    "H2": ({"H": 2}, 200.0, 1000.0, 3500.0,
           [2.34433112e+00, 7.98052075e-03, -1.94781510e-05, 2.01572094e-08,
            -7.37611761e-12, -9.17935173e+02, 6.83010238e-01],
           [3.33727920e+00, -4.94024731e-05, 4.99456778e-07, -1.79566394e-10,
            2.00255376e-14, -9.50158922e+02, -3.20502331e+00]),
    "H": ({"H": 1}, 200.0, 1000.0, 3500.0,
          [2.50000000e+00, 7.05332819e-13, -1.99591964e-15, 2.30081632e-18,
           -9.27732332e-22, 2.54736599e+04, -4.46682853e-01],
          [2.50000001e+00, -2.30842973e-11, 1.61561948e-14, -4.73515235e-18,
           4.98197357e-22, 2.54736599e+04, -4.46682914e-01]),
    "O": ({"O": 1}, 200.0, 1000.0, 3500.0,
          [3.16826710e+00, -3.27931884e-03, 6.64306396e-06, -6.12806624e-09,
           2.11265971e-12, 2.91222592e+04, 2.05193346e+00],
          [2.56942078e+00, -8.59741137e-05, 4.19484589e-08, -1.00177799e-11,
           1.22833691e-15, 2.92175791e+04, 4.78433864e+00]),
    "O2": ({"O": 2}, 200.0, 1000.0, 3500.0,
           [3.78245636e+00, -2.99673416e-03, 9.84730201e-06, -9.68129509e-09,
            3.24372837e-12, -1.06394356e+03, 3.65767573e+00],
           [3.28253784e+00, 1.48308754e-03, -7.57966669e-07, 2.09470555e-10,
            -2.16717794e-14, -1.08845772e+03, 5.45323129e+00]),
    "OH": ({"O": 1, "H": 1}, 200.0, 1000.0, 3500.0,
           [3.99201543e+00, -2.40131752e-03, 4.61793841e-06, -3.88113333e-09,
            1.36411470e-12, 3.61508056e+03, -1.03925458e-01],
           [3.09288767e+00, 5.48429716e-04, 1.26505228e-07, -8.79461556e-11,
            1.17412376e-14, 3.85865700e+03, 4.47669610e+00]),
    "H2O": ({"H": 2, "O": 1}, 200.0, 1000.0, 3500.0,
            [4.19864056e+00, -2.03643410e-03, 6.52040211e-06, -5.48797062e-09,
             1.77197817e-12, -3.02937267e+04, -8.49032208e-01],
            [3.03399249e+00, 2.17691804e-03, -1.64072518e-07, -9.70419870e-11,
             1.68200992e-14, -3.00042971e+04, 4.96677010e+00]),
    "HO2": ({"H": 1, "O": 2}, 200.0, 1000.0, 3500.0,
            [4.30179801e+00, -4.74912051e-03, 2.11582891e-05, -2.42763894e-08,
             9.29225124e-12, 2.94808040e+02, 3.71666245e+00],
            [4.01721090e+00, 2.23982013e-03, -6.33658150e-07, 1.14246370e-10,
             -1.07908535e-14, 1.11856713e+02, 3.78510215e+00]),
    "H2O2": ({"H": 2, "O": 2}, 200.0, 1000.0, 3500.0,
             [4.27611269e+00, -5.42822417e-04, 1.67335701e-05, -2.15770813e-08,
              8.62454363e-12, -1.77025821e+04, 3.43505074e+00],
             [4.16500285e+00, 4.90831694e-03, -1.90139225e-06, 3.71185986e-10,
              -2.91615662e-14, -1.78617877e+04, 2.91615662e+00]),
    "N2": ({"N": 2}, 300.0, 1000.0, 5000.0,
           [3.29867700e+00, 1.40824040e-03, -3.96322200e-06, 5.64151500e-09,
            -2.44485400e-12, -1.02089990e+03, 3.95037200e+00],
           [2.92664000e+00, 1.48797680e-03, -5.68476000e-07, 1.00970380e-10,
            -6.75335100e-15, -9.22797700e+02, 5.98052800e+00]),
    "AR": ({"AR": 1}, 300.0, 1000.0, 5000.0,
           [2.50000000e+00, 0.0, 0.0, 0.0, 0.0, -7.45375000e+02, 4.36600000e+00],
           [2.50000000e+00, 0.0, 0.0, 0.0, 0.0, -7.45375000e+02, 4.36600000e+00]),
}

TRANSPORT = {
    #        geom  eps/k    sigma   dipole  polar   zrot
    "H2":   (1,   38.000,  2.920,  0.000,  0.790, 280.000),
    "H":    (0,  145.000,  2.050,  0.000,  0.000,   0.000),
    "O":    (0,   80.000,  2.750,  0.000,  0.000,   0.000),
    "O2":   (1,  107.400,  3.458,  0.000,  1.600,   3.800),
    "OH":   (1,   80.000,  2.750,  0.000,  0.000,   0.000),
    "H2O":  (2,  572.400,  2.605,  1.844,  0.000,   4.000),
    "HO2":  (2,  107.400,  3.458,  0.000,  0.000,   1.000),
    "H2O2": (2,  107.400,  3.458,  0.000,  0.000,   3.800),
    "N2":   (1,   97.530,  3.621,  0.000,  1.760,   4.000),
    "AR":   (0,  136.500,  3.330,  0.000,  0.000,   0.000),
}

H2O2_REACTIONS = """\
2O+M<=>O2+M                              1.200E+17   -1.000        0.00
H2/2.4/ H2O/15.4/ AR/0.83/
O+H+M<=>OH+M                             5.000E+17   -1.000        0.00
H2/2.0/ H2O/6.0/ AR/0.7/
O+H2<=>H+OH                              3.870E+04    2.700     6260.00
O+HO2<=>OH+O2                            2.000E+13    0.000        0.00
O+H2O2<=>OH+HO2                          9.630E+06    2.000     4000.00
H+O2+M<=>HO2+M                           2.800E+18   -0.860        0.00
O2/0.0/ H2O/0.0/ N2/0.0/ AR/0.0/
H+2O2<=>HO2+O2                           2.080E+19   -1.240        0.00
H+O2+H2O<=>HO2+H2O                       1.126E+19   -0.760        0.00
H+O2+N2<=>HO2+N2                         2.600E+19   -1.240        0.00
H+O2+AR<=>HO2+AR                         7.000E+17   -0.800        0.00
H+O2<=>O+OH                              2.650E+16   -0.671    17041.00
2H+M<=>H2+M                              1.000E+18   -1.000        0.00
H2/0.0/ H2O/0.0/
2H+H2<=>2H2                              9.000E+16   -0.600        0.00
2H+H2O<=>H2+H2O                          6.000E+19   -1.250        0.00
H+OH+M<=>H2O+M                           2.200E+22   -2.000        0.00
H2/0.73/ H2O/3.65/ AR/0.38/
H+HO2<=>O+H2O                            3.970E+12    0.000      671.00
H+HO2<=>O2+H2                            4.480E+13    0.000     1068.00
H+HO2<=>2OH                              8.400E+13    0.000      635.00
H+H2O2<=>HO2+H2                          1.210E+07    2.000     5200.00
H+H2O2<=>OH+H2O                          1.000E+13    0.000     3600.00
OH+H2<=>H+H2O                            2.160E+08    1.510     3430.00
2OH(+M)<=>H2O2(+M)                       7.400E+13   -0.370        0.00
LOW/2.300E+18 -0.900 -1700.00/
TROE/0.7346 94.00 1756.00 5182.00/
H2/2.0/ H2O/6.0/ AR/0.7/
2OH<=>O+H2O                              3.570E+04    2.400    -2110.00
OH+HO2<=>O2+H2O                          1.450E+13    0.000     -500.00
DUPLICATE
OH+HO2<=>O2+H2O                          5.000E+15    0.000    17330.00
DUPLICATE
HO2+HO2<=>O2+H2O2                        1.300E+11    0.000    -1630.00
DUPLICATE
HO2+HO2<=>O2+H2O2                        4.200E+14    0.000    12000.00
DUPLICATE
"""


def nasa_h_RT(c, T):
    return (c[0] + c[1] / 2 * T + c[2] / 3 * T**2 + c[3] / 4 * T**3
            + c[4] / 5 * T**4 + c[5] / T)


def nasa_s_R(c, T):
    return (c[0] * np.log(T) + c[1] * T + c[2] / 2 * T**2 + c[3] / 3 * T**3
            + c[4] / 4 * T**4 + c[6])


def nasa_cp_R(c, T):
    return c[0] + c[1] * T + c[2] * T**2 + c[3] * T**3 + c[4] * T**4


def repair_continuity():
    """Force exact h/s continuity at Tmid by adjusting high-range a6/a7.
    Reports cp discontinuities (unfixable without touching a1..a5)."""
    for name, (comp, tlo, tmid, thi, lo, hi) in THERMO.items():
        cp_jump = nasa_cp_R(hi, tmid) - nasa_cp_R(lo, tmid)
        if abs(cp_jump) > 2e-3:
            print(f"WARNING {name}: cp/R discontinuity {cp_jump:+.2e} at Tmid")
        dh = nasa_h_RT(lo, tmid) - nasa_h_RT(hi, tmid)  # in h/RT units
        hi[5] += dh * tmid
        ds = nasa_s_R(lo, tmid) - nasa_s_R(hi, tmid)
        if abs(ds) > 5e-3:
            print(f"note {name}: adjusting high-range a7 by {ds:+.2e}")
        hi[6] += ds


def fmt_coeff(x):
    s = f"{x: .8E}"  # ' 2.34433112E+00' / '-7.37611761E-12'
    return s


def thermo_card(name, comp, tlo, tmid, thi, lo, hi, index):
    compstr = ""
    items = list(comp.items())[:4]
    for el, n in items:
        compstr += f"{el:<2s}{int(n):>3d}"
    compstr = f"{compstr:<20s}"
    l1 = f"{name:<18s}{'g tpu':<6s}{compstr}G{tlo:10.3f}{thi:10.3f}{tmid:8.2f}"
    l1 = f"{l1:<79s}1"
    c = hi + lo
    l2 = "".join(fmt_coeff(v) for v in c[0:5])
    l2 = f"{l2:<79s}2"
    l3 = "".join(fmt_coeff(v) for v in c[5:10])
    l3 = f"{l3:<79s}3"
    l4 = "".join(fmt_coeff(v) for v in c[10:14])
    l4 = f"{l4:<79s}4"
    return "\n".join([l1, l2, l3, l4])


def write_h2o2():
    species = list(THERMO.keys())
    cards = "\n".join(
        thermo_card(n, *THERMO[n], i + 1) for i, n in enumerate(species))
    therm = ("THERMO ALL\n   200.000  1000.000  5000.000\n"
             + cards + "\nEND\n")
    with open(os.path.join(OUT, "therm_h2o2.dat"), "w") as fh:
        fh.write(therm)
    mech = (
        "! GRI-3.0-derived H2/O2/N2/AR subsystem — embedded fixture for\n"
        "! pychemkin_tpu (reference ships no mechanisms; see tools/gen_mech_data.py)\n"
        "ELEMENTS\nO  H  N  AR\nEND\n"
        "SPECIES\n" + "  ".join(species) + "\nEND\n"
        + therm +
        "REACTIONS\n" + H2O2_REACTIONS + "END\n")
    with open(os.path.join(OUT, "h2o2.inp"), "w") as fh:
        fh.write(mech)
    tran_lines = []
    for n, (g, e, s, d, p, z) in TRANSPORT.items():
        tran_lines.append(
            f"{n:<16s}{g:4d}{e:10.3f}{s:10.3f}{d:10.3f}{p:10.3f}{z:10.3f}")
    with open(os.path.join(OUT, "tran_h2o2.dat"), "w") as fh:
        fh.write("\n".join(tran_lines) + "\n")
    print(f"wrote h2o2 fixture: {len(species)} species")


def write_grisyn(seed=20260729, n_extra_species=43, n_reactions=298):
    """Synthetic GRI-3.0-sized mechanism: the 10 real H2/O2 species plus
    CHON pseudo-species with smooth, consistent NASA-7 fits; 325 reactions
    total (26 real H2/O2 + synthetic), with a GRI-like mix of plain,
    third-body, and Troe-falloff reactions. For PERFORMANCE WORK ONLY."""
    rng = np.random.default_rng(seed)
    species = list(THERMO.keys())
    synth = {}
    for i in range(n_extra_species):
        nC = int(rng.integers(0, 4))
        nH = int(rng.integers(0, 9))
        nO = int(rng.integers(0, 3))
        if nC == 0 and nH == 0 and nO == 0:
            nC, nH = 1, 4
        name = f"S{i:02d}C{nC}H{nH}O{nO}"
        comp = {k: v for k, v in (("C", nC), ("H", nH), ("O", nO)) if v}
        natoms = nC + nH + nO
        # plausible cp/R: rises from ~3+1.5*natoms to ~3+2.5*natoms
        cp0 = 3.0 + 1.2 * natoms + rng.uniform(-0.5, 0.5)
        cp_slope = (0.8 * natoms + rng.uniform(0, 1)) / 3000.0
        a1 = cp0
        a2 = cp_slope
        hf_R = rng.uniform(-3e4, 2e4)  # h_f/R at 0 K-ish
        a6 = hf_R
        a7 = rng.uniform(2.0, 15.0)
        lo = [a1, a2, 0.0, 0.0, 0.0, a6, a7]
        hi = list(lo)
        synth[name] = (comp, 200.0, 1000.0, 3500.0, lo, hi)
    all_thermo = dict(THERMO)
    all_thermo.update(synth)
    species = list(all_thermo.keys())

    # build balanced synthetic reactions: A + B <=> C + D with element balance
    # enforced by constructing products from reactant element pool via a
    # greedy decomposition into existing species.
    comp_of = {n: dict(all_thermo[n][0]) for n in species}
    names = [n for n in species if n not in ("AR", "N2")]
    rxn_lines = []
    count = 0
    attempts = 0
    while count < n_reactions and attempts < 200000:
        attempts += 1
        a, b = rng.choice(names, 2, replace=False)
        pool = {}
        for s_ in (a, b):
            for el, n_ in comp_of[s_].items():
                pool[el] = pool.get(el, 0) + n_
        # find product pair with identical pool
        cands = []
        for c in names:
            rem = dict(pool)
            ok = True
            for el, n_ in comp_of[c].items():
                if rem.get(el, 0) < n_:
                    ok = False
                    break
                rem[el] -= n_
            if not ok:
                continue
            for d in names:
                if comp_of[d] == {el: n_ for el, n_ in rem.items() if n_}:
                    cands.append((c, d))
                    break
        cands = [cd for cd in cands if set(cd) != {a, b}]
        if not cands:
            continue
        c, d = cands[int(rng.integers(0, len(cands)))]
        # IRREVERSIBLE and slow: reversible synthetic reactions with random
        # NASA-7 fits produce astronomically stiff Kc-derived reverse rates
        # that stall any integrator. The benchmark cost is set by the
        # [II, KK] tensor shapes, not the rates, so the synthetic channels
        # are kept kinetically quiet next to the real H2/O2 subsystem.
        A = 10 ** rng.uniform(3, 8)
        beta = rng.uniform(-1.0, 1.0)
        Ea = rng.uniform(30000, 60000)
        kind = rng.uniform()
        eq = f"{a}+{b}=>{c}+{d}"
        if kind < 0.85:
            rxn_lines.append(f"{eq:<48s}{A:10.3E}{beta:9.3f}{Ea:12.2f}")
        elif kind < 0.95:
            eq = f"{a}+{b}+M=>{c}+{d}+M"
            rxn_lines.append(f"{eq:<48s}{A:10.3E}{beta:9.3f}{Ea:12.2f}")
            rxn_lines.append("H2O/6.0/ H2/2.0/")
        else:
            eq = f"{a}+{b}(+M)=>{c}+{d}(+M)"
            rxn_lines.append(f"{eq:<48s}{A:10.3E}{beta:9.3f}{Ea:12.2f}")
            rxn_lines.append(f"LOW/{A*1e3:10.3E} {beta-0.5:6.3f} {max(Ea-2000,0):10.2f}/")
            rxn_lines.append("TROE/0.6 100.0 1500.0 5000.0/")
        count += 1
    if count < n_reactions:
        raise RuntimeError(f"only built {count} synthetic reactions")

    cards = "\n".join(
        thermo_card(n, *all_thermo[n], i + 1) for i, n in enumerate(species))
    mech = (
        "! SYNTHETIC GRI-3.0-sized mechanism (53 species / 325 reactions).\n"
        "! Real H2/O2 subsystem + generated CHON pseudo-species. Tensor shapes\n"
        "! and reaction-type mix match GRI-3.0; NOT a validated chemistry model.\n"
        "! Generated by tools/gen_mech_data.py (seeded, reproducible).\n"
        "ELEMENTS\nO  H  N  AR  C\nEND\n"
        "SPECIES\n" + "\n".join("  ".join(species[i:i + 8])
                                 for i in range(0, len(species), 8)) + "\nEND\n"
        "THERMO ALL\n   200.000  1000.000  5000.000\n" + cards + "\nEND\n"
        "REACTIONS\n" + H2O2_REACTIONS + "\n".join(rxn_lines) + "\nEND\n")
    with open(os.path.join(OUT, "grisyn.inp"), "w") as fh:
        fh.write(mech)
    print(f"wrote grisyn fixture: {len(species)} species, {27 + count} reactions")


if __name__ == "__main__":
    os.makedirs(OUT, exist_ok=True)
    repair_continuity()
    write_h2o2()
    write_grisyn()
