"""Step-cost ablation: where one SDIRK3 step attempt's time goes.

VERDICT round-5 weak #4/#9: the claims "the Jacobian build dominates the
step cost" and "the f32 Jacobian path is the TPU win" existed only as
builder prose. This tool turns them into a captured artifact: it times
each component of one step attempt of the batched stiff integrator —
RHS evaluation (dense and mechanism-specialized sparse, f64 and f32),
the analytical Jacobian under both ROP modes plus the retired
``jacfwd`` build, the pivot-free f32 LU vs the pivoted LU vs the
bordered (Schur-complement) factorization, and the triangular /
bordered solves — on a [B]-batched representative ignition state, and
emits one JSON document (atomic tmp+rename via the telemetry sink)
plus the same JSON on stdout.

Three attempt models ride in the artifact:

- ``attempt_model``        — the hot path since ISSUE 11: sparse ROP
  kernels + analytical Jacobian + bordered Newton solve;
- ``attempt_model_dense``  — the ISSUE-6 hot path (dense ROP kernels,
  analytical Jacobian, full-matrix LU), formula-identical to the
  PR-6 artifact's ``attempt_model`` for cross-round comparability;
- ``attempt_model_ad``     — the retired dense-AD build (the
  ``f64_jac`` rescue rung);
- ``attempt_model_fused``  — the ISSUE-16 fused-emission attempt: ONE
  program returns ``(f, J)`` from a shared ROP evaluation
  (``fj_fused_f64`` component), so the attempt's separate Jacobian
  build and its first Newton RHS collapse into one evaluation. The
  ``fused_vs_split`` block carries the headline pair comparison:
  ``pair_split_s = t_jac_analytic + t_rhs`` vs ``pair_fused_s =
  t_fj`` — what one (Jacobian, RHS) refresh costs on each path.

Each model reports both the historical ``n_newton_assumed = 6`` split
(cross-round comparable) and, when ``--measure-newton`` ran (default),
a second split using the per-attempt Newton iteration count MEASURED
from a real short pre-ignition integration (odeint's ``n_newton`` /
attempts from ``solution_stats``).

Runs on whatever backend JAX selects; CI runs it on CPU (the component
STRUCTURE and the FLOP model are platform-independent; only the
absolute times are). Usage::

    python tools/ablate_step_cost.py --mech h2o2 --batch 32 \
        --repeats 3 --out step_cost_ablation.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                 # noqa: E402
import jax.numpy as jnp                                    # noqa: E402
import jax.scipy.linalg as jsl                             # noqa: E402
import numpy as np                                         # noqa: E402

from pychemkin_tpu import telemetry                        # noqa: E402
from pychemkin_tpu.benchmarks import _flop_model           # noqa: E402
from pychemkin_tpu.mechanism import costmodel, load_embedded  # noqa: E402
from pychemkin_tpu.ops import (                            # noqa: E402
    jacobian, kinetics, linalg, reactors, thermo)
from pychemkin_tpu.ops import odeint as odeint_mod         # noqa: E402
from pychemkin_tpu.ops.odeint import _GAMMA, _cast_floats  # noqa: E402


def _timed(fn, args, repeats):
    """(compile_s, best run_s): first call = compile + run; then
    ``repeats`` fenced calls, best-of."""
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return compile_s, best


def _problem(mech_name: str, B: int):
    """Representative batched ignition problem: stoichiometric H2/air
    (CH4/air for gri30) at a spread of pre-ignition temperatures."""
    mech = load_embedded(mech_name)
    names = list(mech.species_names)
    X = np.zeros(len(names))
    if mech_name == "gri30":
        X[names.index("CH4")] = 1.0
        X[names.index("O2")] = 2.0
        X[names.index("N2")] = 7.52
    else:
        X[names.index("H2")] = 2.0
        X[names.index("O2")] = 1.0
        X[names.index("N2")] = 3.76
    Y0 = np.asarray(thermo.X_to_Y(mech, jnp.asarray(X / X.sum())))
    T0s = np.linspace(1000.0, 1400.0, B)
    P0 = 1.01325e6
    args = reactors.BatchArgs(
        mech=mech,
        constraint=reactors.constant_profile(P0),
        tprof=reactors.constant_profile(1000.0),
        qloss=reactors.constant_profile(0.0),
        area=reactors.constant_profile(0.0),
        mass=float(thermo.density(mech, 1200.0, P0, jnp.asarray(Y0))))
    ys = jnp.asarray(np.concatenate(
        [np.tile(Y0, (B, 1)), T0s[:, None]], axis=1))
    return mech, args, ys


def run_ablation(mech_name: str, B: int, repeats: int,
                 measure_newton: bool = True) -> dict:
    mech, args, ys = _problem(mech_name, B)
    N = mech.n_species + 1
    rhs = reactors.conp_enrg_rhs
    h = 1e-7     # representative pre-ignition step size

    def rhs64(ys):
        return jax.vmap(lambda y: rhs(0.0, y, args))(ys)

    args32 = _cast_floats(args, jnp.float32)

    def rhs32(ys):
        return jax.vmap(lambda y: rhs(jnp.float32(0.0), y, args32))(
            ys.astype(jnp.float32))

    def jac64(ys):
        return jax.vmap(
            lambda y: jax.jacfwd(lambda yy: rhs(0.0, yy, args))(y))(ys)

    def jac32(ys):
        return jax.vmap(lambda y: jax.jacfwd(
            lambda yy: rhs(jnp.float32(0.0), yy, args32))(y))(
            ys.astype(jnp.float32))

    # the analytical closed-form assembly (ops/jacobian.py) — the stiff
    # hot path since ISSUE 6 (jac_mode="analytic"); timed under BOTH
    # ROP modes (its internal rop_intermediates takes whichever kernel
    # the trace-time mode selects). jac_f64/jac_f32 above are the
    # retired dense-AD path, kept as the f64_jac rescue rung.
    def jac_analytic64(ys):
        return jax.vmap(lambda y: jacobian._batch_jac_core(
            "CONP", "ENRG", 0.0, y, args))(ys)

    def jac_analytic32(ys):
        return jax.vmap(lambda y: jacobian._batch_jac_core(
            "CONP", "ENRG", jnp.float32(0.0), y, args32))(
            ys.astype(jnp.float32))

    # the fused (f, J) emission (ISSUE 16): one program, one shared ROP
    # evaluation — timed f64 only (auto never fuses under mixed
    # precision, where the f32 Jacobian cast voids the sharing)
    def fj_fused64(ys):
        return jax.vmap(lambda y: jacobian._batch_jac_core(
            "CONP", "ENRG", 0.0, y, args, with_rhs=True))(ys)

    def newton_matrix(J):
        return jnp.eye(N, dtype=J.dtype) - (h * _GAMMA) * J

    with kinetics.rop_mode("dense"):
        Ms64 = jax.jit(lambda ys: newton_matrix(jac64(ys)))(ys)
        Ms64 = jax.block_until_ready(Ms64)
        bs = rhs64(ys)

    def lu_nopivot(Ms):
        return linalg._lu_nopivot(Ms.astype(jnp.float32))

    def lu_pivoted(Ms):
        return jsl.lu_factor(Ms.astype(jnp.float32))[0]

    def lu_bordered(Ms):
        # the structured factorization the integrator now runs
        # (platform path: exact scipy LU of the species block on CPU,
        # pivot-free f32 on TPU) — factor + Schur complement, vmapped
        # per element exactly as odeint traces it
        return jax.vmap(linalg.factor_bordered)(Ms)

    lus = jax.jit(lu_nopivot)(Ms64)
    lus = jax.block_until_ready(lus)
    fac = linalg.Factorization(lu=lus, piv=None, A=Ms64)
    bfac = jax.jit(lu_bordered)(Ms64)
    bfac = jax.block_until_ready(bfac)

    def tri_solve(bs):
        return linalg._solve_nopivot(lus, bs.astype(jnp.float32))

    def refined_solve(bs):
        return linalg.solve_factored(fac, bs, refine=2,
                                     residual_check=False)

    def bordered_solve(bs):
        # one Newton-direction solve from the prebuilt bordered factor
        return jax.vmap(lambda bf, b: linalg.solve_bordered(
            bf, b, refine=0))(bfac, bs)

    components = {}

    def _run(name, fn, call_args):
        compile_s, run_s = _timed(fn, call_args, repeats)
        components[name] = {"compile_s": round(compile_s, 4),
                            "run_s": round(run_s, 6)}
        print(f"# {name}: {run_s*1e3:.3f} ms/call "
              f"(compile {compile_s:.2f}s)", file=sys.stderr)

    # dense-kernel components (the PR-6 twin's inputs): traced with the
    # ROP mode pinned dense so the twin stays comparable across rounds
    # regardless of platform/env defaults
    with kinetics.rop_mode("dense"):
        for name, fn in [
                ("rhs_f64", jax.jit(rhs64)),
                ("rhs_f32", jax.jit(rhs32)),
                ("jac_f64", jax.jit(jac64)),
                ("jac_f32", jax.jit(jac32)),
                ("jac_analytic_f64", jax.jit(jac_analytic64)),
                ("jac_analytic_f32", jax.jit(jac_analytic32)),
                ("fj_fused_f64", jax.jit(fj_fused64)),
        ]:
            _run(name, fn, (ys,))
    # mechanism-specialized sparse-kernel components (ISSUE 11).
    # Fresh lambda wrappers: jit shares its trace cache for an
    # identical function object, and the ROP mode is a trace-time
    # decision invisible to that cache — re-jitting ``rhs64`` itself
    # here would silently reuse the dense trace.
    with kinetics.rop_mode("sparse"):
        for name, fn in [
                ("rhs_sparse_f64", jax.jit(lambda ys: rhs64(ys))),
                ("rhs_sparse_f32", jax.jit(lambda ys: rhs32(ys))),
                ("jac_sparse_f64",
                 jax.jit(lambda ys: jac_analytic64(ys))),
                ("jac_sparse_f32",
                 jax.jit(lambda ys: jac_analytic32(ys))),
        ]:
            _run(name, fn, (ys,))
    for name, fn in [("lu_nopivot_f32", jax.jit(lu_nopivot)),
                     ("lu_pivoted_f32", jax.jit(lu_pivoted)),
                     ("lu_bordered", jax.jit(lu_bordered))]:
        _run(name, fn, (Ms64,))
    for name, fn in [("tri_solve_f32", jax.jit(tri_solve)),
                     ("tri_solve_refine2", jax.jit(refined_solve)),
                     ("solve_bordered", jax.jit(bordered_solve))]:
        _run(name, fn, (bs,))

    # measured per-attempt Newton iteration count: a real (short,
    # pre-ignition) integration of the same batched problem through
    # odeint; n_newton / (n_steps + n_rejected) replaces the historical
    # assumed 6 (= 3 stages x ~2 iterations) in the *_measured split
    newton_measured = None
    if measure_newton:
        jac_fn = jacobian.batch_rhs_jacobian("CONP", "ENRG")
        ts = jnp.array([0.0, 1e-6])
        atol_vec = jnp.full((N,), 1e-12).at[-1].set(1e-8)
        sol = jax.jit(jax.vmap(lambda y: odeint_mod.odeint(
            rhs, y, ts, args, rtol=1e-6, atol=atol_vec,
            jac=jac_fn)))(ys)
        stats = odeint_mod.solution_stats(sol, label="ablate_measure",
                                          emit=False)
        attempts = stats["n_steps"] + stats["n_rejected"]
        newton_measured = {
            "t_horizon_s": 1e-6,
            "n_steps": stats["n_steps"],
            "n_rejected": stats["n_rejected"],
            "n_newton": stats["n_newton"],
            "n_newton_per_attempt": round(
                stats["n_newton"] / max(attempts, 1), 3),
        }
        print(f"# measured newton/attempt: "
              f"{newton_measured['n_newton_per_attempt']}",
              file=sys.stderr)

    # one SDIRK3 step attempt = 1 Jacobian + 1 factorization + (3
    # stages x ~2 Newton iterations) x (1 f64 RHS + 1 solve) + the
    # error filter solve; shares from the measured component times.
    n_newton = 6
    mixed = linalg.use_mixed_precision()

    def attempt_model(jac_key, lu_key, rhs_key, solve_key):
        t_jac = components[jac_key]["run_s"]
        t_lu = components[lu_key]["run_s"]
        t_rhs = components[rhs_key]["run_s"]
        t_solve = components[solve_key]["run_s"]

        def split(n):
            t_newton = n * (t_rhs + t_solve)
            t_attempt = t_jac + t_lu + t_newton + t_solve
            return t_attempt, t_newton

        t_attempt, t_newton = split(n_newton)
        out = {
            "n_newton_assumed": n_newton,
            "jac_component": jac_key,
            "lu_component": lu_key,
            "rhs_component": rhs_key,
            "solve_component": solve_key,
            "attempt_s": round(t_attempt, 6),
            "jac_pct": round(100 * t_jac / t_attempt, 2),
            "lu_pct": round(100 * t_lu / t_attempt, 2),
            "newton_rhs_solve_pct": round(100 * t_newton / t_attempt, 2),
            "err_filter_pct": round(100 * t_solve / t_attempt, 2),
        }
        if newton_measured is not None:
            n_meas = newton_measured["n_newton_per_attempt"]
            t_att_m, t_new_m = split(n_meas)
            out["n_newton_measured"] = n_meas
            out["attempt_s_measured"] = round(t_att_m, 6)
            out["newton_rhs_solve_pct_measured"] = round(
                100 * t_new_m / t_att_m, 2)
        return out

    def fused_attempt_model(fj_key, lu_key, rhs_key, solve_key):
        t_fj = components[fj_key]["run_s"]
        t_lu = components[lu_key]["run_s"]
        t_rhs = components[rhs_key]["run_s"]
        t_solve = components[solve_key]["run_s"]

        def split(n):
            # the fused program returns the attempt's Jacobian AND its
            # first Newton RHS in one evaluation; the remaining n-1
            # RHS refreshes route through the same program with the J
            # output dead-code-eliminated (~t_rhs each). Every Newton
            # iteration still pays its solve.
            t_newton = (n - 1) * t_rhs + n * t_solve
            t_attempt = t_fj + t_lu + t_newton + t_solve
            return t_attempt, t_newton

        t_attempt, t_newton = split(n_newton)
        out = {
            "n_newton_assumed": n_newton,
            "fj_component": fj_key,
            "lu_component": lu_key,
            "rhs_component": rhs_key,
            "solve_component": solve_key,
            "attempt_s": round(t_attempt, 6),
            "fj_pct": round(100 * t_fj / t_attempt, 2),
            "lu_pct": round(100 * t_lu / t_attempt, 2),
            "newton_rhs_solve_pct": round(100 * t_newton / t_attempt, 2),
            "err_filter_pct": round(100 * t_solve / t_attempt, 2),
        }
        if newton_measured is not None:
            n_meas = newton_measured["n_newton_per_attempt"]
            t_att_m, t_new_m = split(n_meas)
            out["n_newton_measured"] = n_meas
            out["attempt_s_measured"] = round(t_att_m, 6)
            out["newton_rhs_solve_pct_measured"] = round(
                100 * t_new_m / t_att_m, 2)
        return out

    lu_key = "lu_nopivot_f32" if mixed else "lu_pivoted_f32"
    f32_flop, f64_flop = _flop_model(mech, n_steps=1, n_rejected=0,
                                     n_newton=n_newton)

    # the HOT PATH this platform actually runs: sparse ROP only where
    # resolve_rop_mode() lands there for a staged record (CPU by
    # default — on TPU the integrator runs the dense kernels, and the
    # headline model must describe that path, not the sparse twin)
    hot_mode = (kinetics.resolve_rop_mode()
                if mech.rop_stage is not None else "dense")
    if hot_mode == "sparse":
        hot = attempt_model(
            "jac_sparse_f32" if mixed else "jac_sparse_f64",
            "lu_bordered",
            "rhs_sparse_f32" if mixed else "rhs_sparse_f64",
            "solve_bordered")
    else:
        hot = attempt_model(
            "jac_analytic_f32" if mixed else "jac_analytic_f64",
            "lu_bordered", "rhs_f64", "solve_bordered")

    # the remaining attempt models as locals so the analytic FLOP
    # columns below can annotate them before banking
    dense_model = attempt_model(
        "jac_analytic_f32" if mixed else "jac_analytic_f64",
        lu_key, "rhs_f64", "tri_solve_f32")
    fused_model = fused_attempt_model(
        "fj_fused_f64", lu_key, "rhs_f64", "tri_solve_f32")
    ad_model = attempt_model(
        "jac_f32" if mixed else "jac_f64",
        lu_key, "rhs_f64", "tri_solve_f32")

    # analytic FLOP columns (ISSUE 17): closed-form per-attempt counts
    # from the staged COO cardinalities — the SAME model the serving
    # observatory charges per dispatch (mechanism/costmodel.py), so a
    # drift between this artifact and the chemtop programs panel is a
    # model bug, not a bookkeeping difference. Counts are per lane;
    # the columns scale by B to sit next to the per-call times.
    def _model_col(target, rop, jac, solver, fused=False):
        af = costmodel.attempt_flops(
            mech, rop_mode=rop, jac_mode=jac, fused=fused,
            solver=solver, n_newton=n_newton)
        target["model_mflop"] = round(af["total"] * B / 1e6, 3)
        if target.get("attempt_s"):
            target["model_gflops"] = round(
                af["total"] * B / 1e9 / target["attempt_s"], 3)
        return af

    af_hot = _model_col(hot, hot_mode, "analytic", "bordered")
    _model_col(dense_model, "dense", "analytic", "dense")
    _model_col(fused_model, "dense", "analytic", "dense", fused=True)
    _model_col(ad_model, "dense", "ad", "dense")

    # model-vs-measured agreement: a pure FLOP model predicts TIME
    # ratios only between kernels in the same roofline regime, so the
    # gated pairs compare like with like — the two RHS variants (both
    # rate-constant/transcendental-bound) and the fused-vs-split
    # (Jacobian, RHS) pair, which shares its exact kernel set. Ratios
    # cancel the container's absolute speed; agreement_x = how far
    # apart model and measured ratios are, symmetric; the acceptance
    # gate is within_2x on every pair in model_vs_measured.
    #
    # Cross-regime ratios (matmul-bound dense Jacobian over
    # transcendental-bound RHS, scatter-bound sparse Jacobian over
    # sparse RHS) are banked UNGATED under model_cross_class: their
    # divergence is the per-kernel efficiency gap the observatory's
    # mfu_pct exists to measure, not a model error. The independent
    # check on the Jacobian term is component_roofline: the dense
    # analytic Jacobian's model FLOPs over its measured time must sit
    # near the calibrated GEMM roof once the matmul is big enough to
    # be compute-bound (grisyn: ~70-100% of roof across captures,
    # while every non-matmul component sits an order of magnitude
    # below it; h2o2's [10,27]x[27,11] contraction is latency-bound
    # and reported for the record).
    card = costmodel.cardinalities(mech)
    rhs_d = costmodel.rhs_flops(card, "dense")
    rhs_s = costmodel.rhs_flops(card, "sparse")
    jac_d = costmodel.jac_flops(card, "dense", "analytic")
    jac_s = costmodel.jac_flops(card, "sparse", "analytic")
    fj_d = costmodel.fused_flops(card, "dense")
    model_vs_measured = {}
    model_cross_class = {}

    def _pair(name, model_x, measured_x, *, gated=True):
        entry = {"model_x": round(model_x, 3),
                 "measured_x": round(measured_x, 3)}
        if model_x > 0 and measured_x > 0:
            off = max(model_x / measured_x, measured_x / model_x)
            entry["agreement_x"] = round(off, 3)
            if gated:
                entry["within_2x"] = off <= 2.0
        (model_vs_measured if gated else model_cross_class)[name] = entry

    _pair("rhs_dense_vs_sparse", rhs_d / rhs_s,
          components["rhs_f64"]["run_s"]
          / max(components["rhs_sparse_f64"]["run_s"], 1e-12))
    _pair("fused_pair_speedup", (jac_d + rhs_d) / fj_d,
          (components["jac_analytic_f64"]["run_s"]
           + components["rhs_f64"]["run_s"])
          / max(components["fj_fused_f64"]["run_s"], 1e-12))
    _pair("jac_dense_vs_sparse", jac_d / jac_s,
          components["jac_analytic_f64"]["run_s"]
          / max(components["jac_sparse_f64"]["run_s"], 1e-12),
          gated=False)
    _pair("jac_vs_rhs_dense", jac_d / rhs_d,
          components["jac_analytic_f64"]["run_s"]
          / max(components["rhs_f64"]["run_s"], 1e-12),
          gated=False)
    _pair("jac_sparse_vs_rhs_sparse", jac_s / rhs_s,
          components["jac_sparse_f64"]["run_s"]
          / max(components["rhs_sparse_f64"]["run_s"], 1e-12),
          gated=False)

    from pychemkin_tpu.utils import calibration as _calibration

    probe = _calibration.probe()
    component_roofline = {}
    for comp_key, flops in (("rhs_f64", rhs_d), ("rhs_sparse_f64", rhs_s),
                            ("jac_analytic_f64", jac_d),
                            ("jac_sparse_f64", jac_s),
                            ("fj_fused_f64", fj_d)):
        run_s = components.get(comp_key, {}).get("run_s")
        if not run_s:
            continue
        achieved = flops * B / 1e9 / run_s
        row = {"model_mflop": round(flops * B / 1e6, 3),
               "achieved_gflops": round(achieved, 3)}
        roof = probe.get("gemm_gflops")
        if roof:
            row["pct_of_gemm_roof"] = round(100.0 * achieved / roof, 2)
        component_roofline[comp_key] = row

    out = {
        "tool": "ablate_step_cost",
        "platform": jax.devices()[0].platform,
        "mech": mech_name,
        "B": B,
        "n_state": N,
        "repeats": repeats,
        # container-speed fingerprint: lets tools/perf_ledger.py
        # place this capture on the normalized cross-PR trajectory
        "calibration": probe,
        "components": components,
        "sparsity": jacobian.sparsity_stats(mech),
        "newton_measured": newton_measured,
        "staged": mech.rop_stage is not None,
        "rop_mode": hot_mode,
        # the hot path's attempt since ISSUE 11: the resolved ROP
        # kernel (sparse on staged-CPU, dense on TPU) + analytical
        # Jacobian + bordered (Schur-complement) solve
        "attempt_model": hot,
        # the ISSUE-6 hot path (dense ROP, analytical Jacobian, full
        # LU) — formula-identical to the PR-6 artifact's attempt_model,
        # the cross-round comparability twin
        "attempt_model_dense": dense_model,
        # the ISSUE-16 fused attempt: one (f, J) program replaces the
        # dense twin's separate Jacobian build + first Newton RHS
        # (fused is an f64-only path — auto stays split under mixed
        # precision — so the twin comparison is pinned to the f64
        # dense components regardless of platform)
        "attempt_model_fused": fused_model,
        # the retired dense-AD attempt (f64_jac rescue rung)
        "attempt_model_ad": ad_model,
        # the ISSUE-17 agreement block: analytic-model component
        # ratios vs the measured time ratios for same-regime pairs
        # (within_2x per pair is the acceptance gate), plus the
        # ungated cross-regime ratios and the per-component roofline
        # that validate the Jacobian term independently
        "model_vs_measured": model_vs_measured,
        "model_cross_class": model_cross_class,
        "component_roofline": component_roofline,
        # the ISSUE-16 headline: what one (Jacobian, RHS) refresh costs
        # split (two programs, ROP ladder paid twice) vs fused (one
        # program, shared ROP evaluation)
        "fused_vs_split": {
            "pair_split_s": round(
                components["jac_analytic_f64"]["run_s"]
                + components["rhs_f64"]["run_s"], 6),
            "pair_fused_s": round(
                components["fj_fused_f64"]["run_s"], 6),
            "pair_speedup": round(
                (components["jac_analytic_f64"]["run_s"]
                 + components["rhs_f64"]["run_s"])
                / max(components["fj_fused_f64"]["run_s"], 1e-12), 3),
        },
        "analytic_vs_ad": {
            "jac_speedup_f64": round(
                components["jac_f64"]["run_s"]
                / max(components["jac_analytic_f64"]["run_s"], 1e-12), 3),
            "jac_speedup_f32": round(
                components["jac_f32"]["run_s"]
                / max(components["jac_analytic_f32"]["run_s"], 1e-12), 3),
        },
        "sparse_vs_dense": {
            "rhs_speedup_f64": round(
                components["rhs_f64"]["run_s"]
                / max(components["rhs_sparse_f64"]["run_s"], 1e-12), 3),
            "rhs_speedup_f32": round(
                components["rhs_f32"]["run_s"]
                / max(components["rhs_sparse_f32"]["run_s"], 1e-12), 3),
            "jac_speedup_f64": round(
                components["jac_analytic_f64"]["run_s"]
                / max(components["jac_sparse_f64"]["run_s"], 1e-12), 3),
            "bordered_vs_full_factor": round(
                components[lu_key]["run_s"]
                / max(components["lu_bordered"]["run_s"], 1e-12), 3),
            "bordered_vs_tri_solve": round(
                components["tri_solve_f32"]["run_s"]
                / max(components["solve_bordered"]["run_s"], 1e-12), 3),
        },
        "f32_vs_f64": {
            "rhs_speedup": round(components["rhs_f64"]["run_s"]
                                 / max(components["rhs_f32"]["run_s"],
                                       1e-12), 3),
            "jac_speedup": round(components["jac_f64"]["run_s"]
                                 / max(components["jac_f32"]["run_s"],
                                       1e-12), 3),
            "pivot_cost_x": round(components["lu_pivoted_f32"]["run_s"]
                                  / max(components["lu_nopivot_f32"]
                                        ["run_s"], 1e-12), 3),
        },
        "model_flops_per_step": {
            "f32_mflop": round(f32_flop / 1e6, 3),
            "f64_mflop": round(f64_flop / 1e6, 3),
        },
    }
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   choices=["h2o2", "grisyn", "gri30"])
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--no-measure-newton", action="store_true",
                   help="skip the real short integration that measures "
                        "the per-attempt Newton iteration count")
    p.add_argument("--out", default="step_cost_ablation.json")
    args = p.parse_args(argv)

    out = run_ablation(args.mech, args.batch, args.repeats,
                       measure_newton=not args.no_measure_newton)
    telemetry.atomic_write_json(args.out, out)
    telemetry.record_event("ablation", mech=args.mech, B=args.batch,
                           out=os.path.abspath(args.out))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
