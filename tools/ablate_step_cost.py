"""Step-cost ablation: where one SDIRK3 step attempt's time goes.

VERDICT round-5 weak #4/#9: the claims "the Jacobian build dominates the
step cost" and "the f32 Jacobian path is the TPU win" existed only as
builder prose. This tool turns them into a captured artifact: it times
each component of one step attempt of the batched stiff integrator —
RHS evaluation (f64 and f32), the batched ``jacfwd`` Jacobian, the
pivot-free f32 LU vs the pivoted LU, the triangular solves with 0 and 2
refinement sweeps — on a [B]-batched representative ignition state, and
emits one JSON document (atomic tmp+rename via the telemetry sink) plus
the same JSON on stdout.

Runs on whatever backend JAX selects; CI runs it on CPU (the component
STRUCTURE and the FLOP model are platform-independent; only the
absolute times are). Usage::

    python tools/ablate_step_cost.py --mech h2o2 --batch 32 \
        --repeats 3 --out step_cost_ablation.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                 # noqa: E402
import jax.numpy as jnp                                    # noqa: E402
import jax.scipy.linalg as jsl                             # noqa: E402
import numpy as np                                         # noqa: E402

from pychemkin_tpu import telemetry                        # noqa: E402
from pychemkin_tpu.benchmarks import _flop_model           # noqa: E402
from pychemkin_tpu.mechanism import load_embedded          # noqa: E402
from pychemkin_tpu.ops import (                            # noqa: E402
    jacobian, linalg, reactors, thermo)
from pychemkin_tpu.ops.odeint import _GAMMA, _cast_floats  # noqa: E402


def _timed(fn, args, repeats):
    """(compile_s, best run_s): first call = compile + run; then
    ``repeats`` fenced calls, best-of."""
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return compile_s, best


def _problem(mech_name: str, B: int):
    """Representative batched ignition problem: stoichiometric H2/air
    (CH4/air for gri30) at a spread of pre-ignition temperatures."""
    mech = load_embedded(mech_name)
    names = list(mech.species_names)
    X = np.zeros(len(names))
    if mech_name == "gri30":
        X[names.index("CH4")] = 1.0
        X[names.index("O2")] = 2.0
        X[names.index("N2")] = 7.52
    else:
        X[names.index("H2")] = 2.0
        X[names.index("O2")] = 1.0
        X[names.index("N2")] = 3.76
    Y0 = np.asarray(thermo.X_to_Y(mech, jnp.asarray(X / X.sum())))
    T0s = np.linspace(1000.0, 1400.0, B)
    P0 = 1.01325e6
    args = reactors.BatchArgs(
        mech=mech,
        constraint=reactors.constant_profile(P0),
        tprof=reactors.constant_profile(1000.0),
        qloss=reactors.constant_profile(0.0),
        area=reactors.constant_profile(0.0),
        mass=float(thermo.density(mech, 1200.0, P0, jnp.asarray(Y0))))
    ys = jnp.asarray(np.concatenate(
        [np.tile(Y0, (B, 1)), T0s[:, None]], axis=1))
    return mech, args, ys


def run_ablation(mech_name: str, B: int, repeats: int) -> dict:
    mech, args, ys = _problem(mech_name, B)
    N = mech.n_species + 1
    rhs = reactors.conp_enrg_rhs
    h = 1e-7     # representative pre-ignition step size

    def rhs64(ys):
        return jax.vmap(lambda y: rhs(0.0, y, args))(ys)

    args32 = _cast_floats(args, jnp.float32)

    def rhs32(ys):
        return jax.vmap(lambda y: rhs(jnp.float32(0.0), y, args32))(
            ys.astype(jnp.float32))

    def jac64(ys):
        return jax.vmap(
            lambda y: jax.jacfwd(lambda yy: rhs(0.0, yy, args))(y))(ys)

    def jac32(ys):
        return jax.vmap(lambda y: jax.jacfwd(
            lambda yy: rhs(jnp.float32(0.0), yy, args32))(y))(
            ys.astype(jnp.float32))

    # the analytical closed-form assembly (ops/jacobian.py) — what the
    # stiff hot path now runs by default (jac_mode="analytic"); the
    # jac_f64/jac_f32 AD components above are the retired dense path,
    # kept as the f64_jac rescue rung
    def jac_analytic64(ys):
        return jax.vmap(lambda y: jacobian._batch_jac_core(
            "CONP", "ENRG", 0.0, y, args))(ys)

    def jac_analytic32(ys):
        return jax.vmap(lambda y: jacobian._batch_jac_core(
            "CONP", "ENRG", jnp.float32(0.0), y, args32))(
            ys.astype(jnp.float32))

    def newton_matrix(J):
        return jnp.eye(N, dtype=J.dtype) - (h * _GAMMA) * J

    Ms64 = jax.jit(lambda ys: newton_matrix(jac64(ys)))(ys)
    Ms64 = jax.block_until_ready(Ms64)
    bs = rhs64(ys)

    def lu_nopivot(Ms):
        return linalg._lu_nopivot(Ms.astype(jnp.float32))

    def lu_pivoted(Ms):
        return jsl.lu_factor(Ms.astype(jnp.float32))[0]

    lus = jax.jit(lu_nopivot)(Ms64)
    lus = jax.block_until_ready(lus)
    fac = linalg.Factorization(lu=lus, piv=None, A=Ms64)

    def tri_solve(bs):
        return linalg._solve_nopivot(lus, bs.astype(jnp.float32))

    def refined_solve(bs):
        return linalg.solve_factored(fac, bs, refine=2,
                                     residual_check=False)

    components = {}
    for name, fn in [
            ("rhs_f64", jax.jit(rhs64)),
            ("rhs_f32", jax.jit(rhs32)),
            ("jac_f64", jax.jit(jac64)),
            ("jac_f32", jax.jit(jac32)),
            ("jac_analytic_f64", jax.jit(jac_analytic64)),
            ("jac_analytic_f32", jax.jit(jac_analytic32)),
            ("lu_nopivot_f32", jax.jit(lu_nopivot)),
            ("lu_pivoted_f32", jax.jit(lu_pivoted)),
    ]:
        compile_s, run_s = _timed(fn, (Ms64,) if name.startswith("lu")
                                  else (ys,), repeats)
        components[name] = {"compile_s": round(compile_s, 4),
                            "run_s": round(run_s, 6)}
        print(f"# {name}: {run_s*1e3:.3f} ms/call "
              f"(compile {compile_s:.2f}s)", file=sys.stderr)
    for name, fn in [("tri_solve_f32", jax.jit(tri_solve)),
                     ("tri_solve_refine2", jax.jit(refined_solve))]:
        compile_s, run_s = _timed(fn, (bs,), repeats)
        components[name] = {"compile_s": round(compile_s, 4),
                            "run_s": round(run_s, 6)}
        print(f"# {name}: {run_s*1e3:.3f} ms/call "
              f"(compile {compile_s:.2f}s)", file=sys.stderr)

    # one SDIRK3 step attempt = 1 Jacobian + 1 LU + (3 stages x ~2
    # Newton iterations) x (1 f64 RHS + 1 triangular solve) + the error
    # filter solve; shares from the measured component times. Two
    # attempt models: the analytical Jacobian (jac_mode="analytic", the
    # hot-path default since ISSUE 6) and the retired dense-AD build
    # (the f64_jac rescue rung) — before/after in one artifact.
    n_newton = 6
    mixed = linalg.use_mixed_precision()
    lu_key = "lu_nopivot_f32" if mixed else "lu_pivoted_f32"
    t_lu = components[lu_key]["run_s"]
    t_newton = n_newton * (components["rhs_f64"]["run_s"]
                           + components["tri_solve_f32"]["run_s"])
    t_err = components["tri_solve_f32"]["run_s"]

    def attempt_model(jac_key):
        t_jac = components[jac_key]["run_s"]
        t_attempt = t_jac + t_lu + t_newton + t_err
        return {
            "n_newton_assumed": n_newton,
            "jac_component": jac_key,
            "attempt_s": round(t_attempt, 6),
            "jac_pct": round(100 * t_jac / t_attempt, 2),
            "lu_pct": round(100 * t_lu / t_attempt, 2),
            "newton_rhs_solve_pct": round(100 * t_newton / t_attempt, 2),
            "err_filter_pct": round(100 * t_err / t_attempt, 2),
        }

    f32_flop, f64_flop = _flop_model(mech, n_steps=1, n_rejected=0,
                                     n_newton=n_newton)

    out = {
        "tool": "ablate_step_cost",
        "platform": jax.devices()[0].platform,
        "mech": mech_name,
        "B": B,
        "n_state": N,
        "repeats": repeats,
        "components": components,
        "sparsity": jacobian.sparsity_stats(mech),
        # the hot path's attempt (analytical Jacobian, the default)
        "attempt_model": attempt_model(
            "jac_analytic_f32" if mixed else "jac_analytic_f64"),
        # the retired dense-AD attempt (f64_jac rescue rung) — the
        # "before" split this artifact's earlier revisions reported
        "attempt_model_ad": attempt_model(
            "jac_f32" if mixed else "jac_f64"),
        "analytic_vs_ad": {
            "jac_speedup_f64": round(
                components["jac_f64"]["run_s"]
                / max(components["jac_analytic_f64"]["run_s"], 1e-12), 3),
            "jac_speedup_f32": round(
                components["jac_f32"]["run_s"]
                / max(components["jac_analytic_f32"]["run_s"], 1e-12), 3),
        },
        "f32_vs_f64": {
            "rhs_speedup": round(components["rhs_f64"]["run_s"]
                                 / max(components["rhs_f32"]["run_s"],
                                       1e-12), 3),
            "jac_speedup": round(components["jac_f64"]["run_s"]
                                 / max(components["jac_f32"]["run_s"],
                                       1e-12), 3),
            "pivot_cost_x": round(components["lu_pivoted_f32"]["run_s"]
                                  / max(components["lu_nopivot_f32"]
                                        ["run_s"], 1e-12), 3),
        },
        "model_flops_per_step": {
            "f32_mflop": round(f32_flop / 1e6, 3),
            "f64_mflop": round(f64_flop / 1e6, 3),
        },
    }
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="h2o2",
                   choices=["h2o2", "grisyn", "gri30"])
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--out", default="step_cost_ablation.json")
    args = p.parse_args(argv)

    out = run_ablation(args.mech, args.batch, args.repeats)
    telemetry.atomic_write_json(args.out, out)
    telemetry.record_event("ablation", mech=args.mech, B=args.batch,
                           out=os.path.abspath(args.out))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
