#!/usr/bin/env python
"""perf_ledger — the calibrated cross-PR performance ledger.

Every round has banked perf artifacts (``BENCH_r*.json`` bench
summaries, ``STEP_COST_*.json`` step-cost ablations,
``BATCH_EFF_*.json`` batch-efficiency rungs, ``MULTICHIP_*.json``
multi-device compaction benches — rounds with the pre-ISSUE-16
dryrun-transcript shape carry no metrics and are skipped —
and ``FLEET_*.json`` chemtop snapshots, whose program-observatory
block contributes per-compiled-program rows: per-dispatch wall,
analytic model FLOPs, achieved GFLOP/s, and wall-attribution
coverage), and every
round's notes
carry the same caveat: the container speed drifted, so raw numbers
from different captures do not compare. This tool turns those
artifacts into ONE normalized time series and gives CI the missing
cross-PR regression gate:

- **ingest** (default): scan the repo root (or ``--artifacts`` paths)
  for known artifact families, extract each one's headline metrics,
  divide out the container speed wherever the artifact carries a
  ``calibration`` block (the fixed microprobe of
  ``pychemkin_tpu/utils/calibration.py`` — banked into every rung
  since ISSUE 14; older artifacts ride along flagged
  ``calibrated: false``), and write the ledger JSON
  (``--out``, default ``PERF_LEDGER.json``).

- ``--check CAPTURE``: compare a fresh capture (a bench summary from
  ``BENCH_BANK_PATH``, or any single artifact of a known family)
  against the committed ledger's most recent comparable entry — same
  family, mechanism, and platform. A metric that regresses beyond the
  noise band (``--band``, default 1.5x — the stated tolerance for
  timer noise plus residual calibration error) fails with rc 1 and
  names the metric, the baseline artifact, and both values. When both
  sides carry a calibration block the comparison is between
  NORMALIZED values (container drift divided out); otherwise it falls
  back to raw values and says so. A ledger entry whose backing
  artifact file is missing from ``--root`` fails the check outright
  (rc 1, naming the files) — an unauditable baseline gates nothing.

Usage::

    python tools/perf_ledger.py --out PERF_LEDGER.json
    python tools/perf_ledger.py --check /tmp/bench_bank.json
    python tools/perf_ledger.py --check BENCH_r05.json --band 2.0
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: ledger schema version
LEDGER_VERSION = 1

#: metric name -> better direction. "lower" metrics normalize by
#: MULTIPLYING with the container speed factor (time as-if on the
#: reference container), "higher" metrics by dividing.
METRIC_DIRECTIONS: Dict[str, str] = {
    "throughput": "higher",
    "steps_per_sec": "higher",
    "serve_p50_ms": "lower",
    "serve_p99_ms": "lower",
    "surrogate_p50_ms": "lower",
    "attempt_ms": "lower",
    "attempt_ms_measured": "lower",
    "static_ms_per_elem_top": "lower",
    "sched_ms_per_elem_top": "lower",
    "speedup_top": "higher",
    "rebin_ms_per_elem": "lower",
    "sort_only_ms_per_elem": "lower",
    "rebin_speedup": "higher",
}


def _direction(name: str) -> str:
    """Better-direction for a metric, including the DYNAMIC families
    the exact table cannot enumerate (the per-program fleet rows are
    keyed by content-addressed program ids)."""
    if name in METRIC_DIRECTIONS:
        return METRIC_DIRECTIONS[name]
    if name.endswith(("_gflops", "_speedup", "coverage", "mfu_pct",
                      "_gflop_per_dispatch")):
        return "higher"
    return "lower"


def _calibration_free(name: str) -> bool:
    """Metrics that are COUNTS, not speeds — analytic FLOP totals and
    attribution ratios are container-independent, so normalizing them
    by the speed factor would manufacture drift."""
    return name.endswith(("_mflop", "coverage", "mfu_pct",
                          "_gflop_per_dispatch"))


def _calibration_module():
    """``pychemkin_tpu/utils/calibration.py`` loaded STANDALONE (the
    ledger must work without importing the jax-importing package
    ``__init__`` — same contract as run_suite's sink loading)."""
    path = os.path.join(_REPO, "pychemkin_tpu", "utils",
                        "calibration.py")
    spec = importlib.util.spec_from_file_location("_perf_ledger_cal",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# extractors: one per artifact family -> (kind, context, metrics,
# calibration) or None when the file is not of this family / carries
# no usable numbers

def _bench_summary(doc: Dict) -> Optional[Dict]:
    """A bench summary: either the banked ``BENCH_BANK_PATH`` /
    stdout-summary shape (flat, with ``metric``/``value``) or the
    committed ``BENCH_r*.json`` wrapper (``{"parsed": summary}``)."""
    summary = doc.get("parsed") if isinstance(doc.get("parsed"),
                                              dict) else doc
    if not isinstance(summary, dict) or "value" not in summary \
            or "metric" not in summary:
        return None
    metrics: Dict[str, float] = {}
    if summary.get("value"):
        metrics["throughput"] = float(summary["value"])
    if summary.get("steps_per_sec"):
        metrics["steps_per_sec"] = float(summary["steps_per_sec"])
    serve = summary.get("serve_latency") or {}
    for src, dst in (("p50_ms", "serve_p50_ms"),
                     ("p99_ms", "serve_p99_ms")):
        if serve.get(src) is not None:
            metrics[dst] = float(serve[src])
    sur = summary.get("surrogate_latency") or {}
    if sur.get("surrogate_p50_ms") is not None:
        metrics["surrogate_p50_ms"] = float(sur["surrogate_p50_ms"])
    if not metrics:
        return None
    # mech rides inside the headline metric string ("... (grisyn, ...")
    mech = None
    m = summary.get("metric", "")
    if "(" in m:
        mech = m.split("(", 1)[1].split(",", 1)[0].strip() or None
    return {"kind": "bench",
            "platform": summary.get("platform"),
            "mech": mech, "B": summary.get("B"),
            "metrics": metrics,
            "calibration": summary.get("calibration")}


def _step_cost(doc: Dict) -> Optional[Dict]:
    if doc.get("tool") != "ablate_step_cost":
        return None
    am = doc.get("attempt_model") or {}
    metrics: Dict[str, float] = {}
    if am.get("attempt_s"):
        metrics["attempt_ms"] = float(am["attempt_s"]) * 1e3
    if am.get("attempt_s_measured"):
        metrics["attempt_ms_measured"] = \
            float(am["attempt_s_measured"]) * 1e3
    # the ISSUE-17 analytic columns: model FLOP count (calibration-
    # free — a count regression means the cost model or the staging
    # cardinalities moved) and model throughput over the measured
    # attempt (a speed, normalized like any other)
    if am.get("model_mflop"):
        metrics["attempt_model_mflop"] = float(am["model_mflop"])
    if am.get("model_gflops"):
        metrics["attempt_model_gflops"] = float(am["model_gflops"])
    if not metrics:
        return None
    return {"kind": "step_cost", "platform": doc.get("platform"),
            "mech": doc.get("mech"), "B": doc.get("B"),
            "metrics": metrics,
            "calibration": doc.get("calibration")}


def _batch_eff(doc: Dict) -> Optional[Dict]:
    if doc.get("rung") != "batch_efficiency":
        return None
    per_B = doc.get("per_B") or []
    metrics: Dict[str, float] = {}
    if per_B:
        top = max(per_B, key=lambda r: r.get("B", 0))
        for src, dst in (("static_ms_per_elem",
                          "static_ms_per_elem_top"),
                         ("sched_ms_per_elem",
                          "sched_ms_per_elem_top")):
            if top.get(src) is not None:
                metrics[dst] = float(top[src])
    if doc.get("speedup_top") is not None:
        metrics["speedup_top"] = float(doc["speedup_top"])
    if not metrics:
        return None
    return {"kind": "batch_eff", "platform": doc.get("platform"),
            "mech": doc.get("mech"), "B": None,
            "metrics": metrics,
            "calibration": doc.get("calibration")}


def _multichip(doc: Dict) -> Optional[Dict]:
    """The ``tools/bench_multichip.py`` artifact (``MULTICHIP_r06``
    on). Rounds 1-5 banked the family as a dryrun transcript
    (rc + output tail, no numbers) — those files extract to None and
    are skipped, by design."""
    if doc.get("tool") != "bench_multichip":
        return None
    metrics: Dict[str, float] = {}
    for name in ("rebin_ms_per_elem", "sort_only_ms_per_elem",
                 "rebin_speedup"):
        if doc.get(name) is not None:
            metrics[name] = float(doc[name])
    if not metrics:
        return None
    return {"kind": "multichip", "platform": doc.get("platform"),
            "mech": doc.get("mech"), "B": doc.get("B"),
            "metrics": metrics,
            "calibration": doc.get("calibration")}


def _fleet_snapshot(doc: Dict) -> Optional[Dict]:
    """A ``chemtop --once --out`` fleet snapshot carrying the program
    observatory block (``FLEET_*.json``). Each registered program
    becomes a row of per-dispatch wall, per-dispatch analytic model
    FLOPs, and achieved GFLOP/s — program ids are content-addressed
    (mech+kind+shape+config), so the same id across captures IS the
    same compiled program and the rows gate like any other metric.
    Coverage (attributed wall over measured solver wall) rides along:
    a coverage drop means dispatches stopped being attributed."""
    prog = doc.get("programs")
    if not isinstance(prog, dict) or "n_backends" not in doc:
        return None
    metrics: Dict[str, float] = {}
    for pid, row in sorted((prog.get("by_id") or {}).items()):
        n = int(row.get("dispatches") or 0)
        wall = float(row.get("wall_ms") or 0.0)
        if n > 0 and wall > 0:
            metrics[f"prog.{pid}.ms_per_dispatch"] = round(wall / n, 6)
            gflop = float(row.get("model_gflop_sum") or 0.0)
            if gflop > 0:
                metrics[f"prog.{pid}.model_gflop_per_dispatch"] = \
                    round(gflop / n, 6)
        if row.get("achieved_gflops"):
            metrics[f"prog.{pid}.achieved_gflops"] = \
                float(row["achieved_gflops"])
    if prog.get("coverage") is not None:
        metrics["program_wall_coverage"] = float(prog["coverage"])
    if not metrics:
        return None
    cal = doc.get("calibration")
    if isinstance(cal, list):
        cal = cal[0] if cal else None
    return {"kind": "fleet", "platform": None, "mech": None,
            "B": None, "metrics": metrics, "calibration": cal}


_EXTRACTORS = (_bench_summary, _step_cost, _batch_eff, _multichip,
               _fleet_snapshot)


def extract(path: str) -> Optional[Dict]:
    """One artifact file -> one ledger entry (or None when the file is
    not a known perf-artifact family). Unreadable/torn files yield
    None — a ledger build must survive one bad artifact."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict):
        return None
    for ex in _EXTRACTORS:
        entry = ex(doc)
        if entry is not None:
            entry["artifact"] = os.path.basename(path)
            return entry
    return None


def _normalize(entry: Dict, cal_mod) -> Dict:
    """Attach ``speed_factor``/``calibrated``/``normalized`` to one
    extracted entry. Lower-is-better metrics scale UP on a fast
    container (time as-if on the reference box); higher-is-better
    scale down."""
    factor = cal_mod.speed_factor(entry.get("calibration"))
    entry["speed_factor"] = (round(factor, 4)
                             if factor is not None else None)
    entry["calibrated"] = factor is not None
    normalized: Dict[str, Optional[float]] = {}
    for name, raw in entry["metrics"].items():
        if factor is None:
            normalized[name] = None
        elif _calibration_free(name):
            normalized[name] = raw
        elif _direction(name) == "higher":
            normalized[name] = round(raw / factor, 4)
        else:
            normalized[name] = round(raw * factor, 4)
    entry["normalized"] = normalized
    return entry


def discover(root: str) -> List[str]:
    """The committed perf artifacts in ``root``, name-sorted (the
    ``_rNN`` convention makes that chronological for the bench
    series)."""
    out = []
    for name in sorted(os.listdir(root)):
        if name.endswith(".json") and (
                name.startswith("BENCH_")
                or name.startswith("STEP_COST_")
                or name.startswith("BATCH_EFF_")
                or name.startswith("MULTICHIP_")
                or name.startswith("FLEET_")):
            out.append(os.path.join(root, name))
    return out


def build_ledger(paths: List[str]) -> Dict:
    cal_mod = _calibration_module()
    entries = []
    for p in paths:
        entry = extract(p)
        if entry is None:
            print(f"# perf_ledger: skipping {os.path.basename(p)} "
                  "(not a known perf artifact / no usable metrics)",
                  file=sys.stderr)
            continue
        entries.append(_normalize(entry, cal_mod))
    return {
        "version": LEDGER_VERSION,
        "probe_version": cal_mod.PROBE_VERSION,
        "ref_gemm_gflops": cal_mod.REF_GEMM_GFLOPS,
        "n_entries": len(entries),
        "n_calibrated": sum(1 for e in entries if e["calibrated"]),
        "entries": entries,
    }


# ---------------------------------------------------------------------------
# the regression gate

def _baseline_for(ledger: Dict, capture: Dict) -> Optional[Dict]:
    """Most recent ledger entry comparable to ``capture``: same
    family and mechanism, same platform (a cpu-vs-tpu comparison is
    not a regression signal), and not the capture artifact itself."""
    best = None
    for e in ledger.get("entries", []):
        if e.get("kind") != capture.get("kind"):
            continue
        if e.get("mech") != capture.get("mech"):
            continue
        if e.get("platform") != capture.get("platform"):
            continue
        if e.get("artifact") == capture.get("artifact"):
            continue
        best = e                     # entries are chronological
    return best


def check(ledger: Dict, capture_path: str, band: float) -> Tuple[int,
                                                                 Dict]:
    """Gate one fresh capture against the ledger. Returns (rc,
    verdict-dict); rc 1 = at least one metric regressed beyond
    ``band``."""
    cal_mod = _calibration_module()
    capture = extract(capture_path)
    if capture is None:
        return 2, {"error": f"{capture_path} is not a recognizable "
                            "perf artifact"}
    capture = _normalize(capture, cal_mod)
    baseline = _baseline_for(ledger, capture)
    verdict: Dict[str, Any] = {
        "capture": capture["artifact"],
        "capture_calibrated": capture["calibrated"],
        "band": band,
        "baseline": baseline["artifact"] if baseline else None,
        "metrics": {},
        "regressions": [],
    }
    if baseline is None:
        # nothing comparable committed yet: a pass WITH a visible
        # reason, never a silent green
        verdict["note"] = ("no comparable baseline (kind/mech/"
                           "platform) in the ledger — nothing to "
                           "gate against")
        return 0, verdict
    for name, raw in capture["metrics"].items():
        base_raw = baseline["metrics"].get(name)
        if base_raw is None:
            continue
        use_norm = (capture["normalized"].get(name) is not None
                    and baseline["normalized"].get(name) is not None)
        new = capture["normalized"][name] if use_norm else raw
        old = (baseline["normalized"][name] if use_norm
               else base_raw)
        direction = _direction(name)
        if old <= 0 or new <= 0:
            continue
        # ratio > 1 means WORSE in both directions
        ratio = new / old if direction == "lower" else old / new
        row = {"new": new, "baseline": old,
               "normalized": use_norm, "direction": direction,
               "worse_ratio": round(ratio, 4)}
        verdict["metrics"][name] = row
        if ratio > band:
            verdict["regressions"].append(name)
    return (1 if verdict["regressions"] else 0), verdict


def missing_artifacts(ledger: Dict, root: str) -> List[str]:
    """Ledger entries whose backing artifact file is gone from
    ``root``. A ledger row without its artifact is an unauditable
    baseline — --check refuses to gate against such a ledger."""
    missing = []
    for e in ledger.get("entries", []):
        name = e.get("artifact")
        if name and not os.path.exists(os.path.join(root, name)):
            missing.append(name)
    return missing


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default=_REPO,
                   help="repo root holding the committed artifacts")
    p.add_argument("--artifacts", nargs="*", default=None,
                   help="explicit artifact paths (overrides "
                        "discovery)")
    p.add_argument("--out", default=None,
                   help="write the ledger JSON here (default: "
                        "PERF_LEDGER.json under --root for ingest; "
                        "not written in --check mode unless given)")
    p.add_argument("--ledger", default=None,
                   help="use a previously built ledger JSON for "
                        "--check instead of rebuilding from --root")
    p.add_argument("--check", default=None, metavar="CAPTURE",
                   help="gate a fresh capture against the ledger; "
                        "rc 1 on regression beyond the band")
    p.add_argument("--band", type=float, default=1.5,
                   help="noise band for --check: fail when a metric "
                        "is worse by more than this ratio "
                        "(default 1.5)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.ledger:
        with open(args.ledger) as f:
            ledger = json.load(f)
    else:
        paths = (args.artifacts if args.artifacts
                 else discover(args.root))
        ledger = build_ledger(paths)
    if args.check:
        gone = missing_artifacts(ledger, args.root)
        if gone:
            print(json.dumps({"error": "ledger entries reference "
                              "missing artifact files",
                              "missing": gone}))
            print("# perf_ledger: MISSING ARTIFACTS: "
                  + ", ".join(gone), file=sys.stderr)
            return 1
        rc, verdict = check(ledger, args.check, args.band)
        print(json.dumps(verdict))
        if rc == 1:
            print("# perf_ledger: REGRESSION beyond "
                  f"{args.band:g}x band: "
                  + ", ".join(verdict["regressions"]),
                  file=sys.stderr)
        return rc
    out = args.out or os.path.join(args.root, "PERF_LEDGER.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, out)
    print(json.dumps({"ledger": out,
                      "n_entries": ledger["n_entries"],
                      "n_calibrated": ledger["n_calibrated"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
