"""Multi-device compaction bench: the MULTICHIP_rNN artifact producer.

Rounds 1-5 banked ``MULTICHIP_r*.json`` as a dryrun transcript (rc +
output tail of ``__graft_entry__.dryrun_multichip`` — a correctness
smoke, no numbers). ISSUE 16 gives the family metrics: this tool runs
the ignition-SCREENING sweep (the ``batch_efficiency`` mix: wide
T0/phi/P straddling the ignition boundary, seed 0) on a FORCED
N-device host mesh and times the cross-shard re-binned compaction
path against the sort-only multi-device path it replaces:

- **re-binned** — ``schedule="sorted"`` with ``PYCHEMKIN_MESH_COMPACT``
  on (the default): every round runs shard_mapped across the mesh,
  survivors re-bin globally into the halving ladder between rounds;
- **sort-only** — the same sweep with ``PYCHEMKIN_MESH_COMPACT=0``:
  cohort sorting but full width to the last straggler (the pre-ISSUE-16
  multi-device behaviour);
- **single-device compacted** — the caller-order fidelity oracle:
  the same conditions through the same kernel on a 1-device mesh.

Two hard claims ride in the artifact beside the timings. First, the
re-binned results **match the single-device compacted sweep in caller
order**: bitwise where XLA:CPU lowers the per-device and single-device
program widths identically (h2o2 — property-tested in
tests/test_schedule.py), and within 1e-9 relative with identical
ok/status/finite patterns on GRI-scale mechanisms, whose per-lane math
picks up ~1e-13 fusion rounding between widely differing program
widths (the band the batch-efficiency rung documents). Lanes sitting
exactly on the step-attempt budget boundary are excluded from the
status comparison — a last-bit difference there legitimately flips
``BUDGET_EXHAUSTED`` <-> ``OK`` (counted in ``n_boundary_lanes``).
Second, the timed re-binned pass triggers **zero new XLA compiles**
after per-rung warmup (every shard_mapped rung program's ``jax.jit``
cache size is constant across the timed pass).

The device count is forced BEFORE jax imports via
``--xla_force_host_platform_device_count`` — run standalone::

    python tools/bench_multichip.py --devices 8 --batch 256 \
        --mech grisyn --out MULTICHIP_r06.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _force_devices(n: int) -> None:
    """Pin the CPU backend and force ``n`` host devices. Must run
    before jax is imported (XLA reads the flag at backend init)."""
    assert "jax" not in sys.modules, \
        "--devices must be applied before jax imports"
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def run_bench(mech_name: str, B: int, n_devices: int, t_end,
              max_steps: int) -> dict:
    import jax
    import numpy as np

    from pychemkin_tpu import parallel, schedule, telemetry
    from pychemkin_tpu.benchmarks import _PROTOCOL
    from pychemkin_tpu.mechanism import load_embedded
    from pychemkin_tpu.resilience.status import SolveStatus
    from pychemkin_tpu.schedule import compaction
    from pychemkin_tpu.surrogate.dataset import phi_composition
    from pychemkin_tpu.utils import calibration

    devices = jax.devices()
    assert devices[0].platform == "cpu", devices
    assert len(devices) >= n_devices, (
        f"need {n_devices} devices, have {len(devices)}")
    _, t_end_proto, rtol, atol = _PROTOCOL[mech_name]
    t_end = float(t_end if t_end is not None else t_end_proto)
    mech = load_embedded(mech_name)

    # the batch_efficiency screening mix, verbatim (seed 0): wide
    # temperature (cold lanes never ignite, marginal lanes grind),
    # wide equivalence ratio, 1-2 atm
    rng = np.random.default_rng(0)
    T0s = rng.uniform(700.0, 1500.0, B)
    phis = rng.uniform(0.5, 2.0, B)
    P0s = 1.01325e6 * (1.0 + rng.uniform(0.0, 1.0, B))
    Y0s = np.stack([phi_composition(mech, float(p))[0] for p in phis])

    mesh_n = parallel.make_mesh(n_devices)
    mesh_1 = parallel.make_mesh(1)
    rec = telemetry.get_recorder()

    def sweep(mesh, t_ends_arr, job_report=None):
        return parallel.sharded_ignition_sweep(
            mech, "CONP", "ENRG", T0s, P0s, Y0s, t_ends_arr,
            mesh=mesh, rtol=rtol, atol=atol,
            max_steps_per_segment=max_steps, schedule="sorted",
            job_report=job_report)

    unit = 8 * n_devices      # MIN_BUCKET lanes per shard
    ladder = compaction.compaction_ladder(B, lane_multiple=unit)

    def warm(mesh, lane_multiple):
        # compile-only warmup: a vanishing-horizon sweep compiles the
        # full-width programs, then each narrow ladder rung compiles
        # from an explicit width-sized tiny sweep (narrow rungs never
        # run at a tiny horizon — everything finishes in round 1)
        sweep(mesh, np.full(B, 1e-7))
        for w in compaction.compaction_ladder(
                B, lane_multiple=lane_multiple):
            sel = np.minimum(np.arange(w), B - 1)
            schedule.compacted_ignition_sweep(
                mech, "CONP", "ENRG", T0s[sel], P0s[sel], Y0s[sel],
                np.full(w, 1e-7), ladder=(w,), rtol=rtol, atol=atol,
                max_steps_per_segment=max_steps,
                mesh=mesh if mesh.devices.size > 1 else None)

    t_ends = np.full(B, t_end)

    # --- pass 1: mesh, re-binned (the ISSUE-16 path) ----------------
    assert os.environ.get("PYCHEMKIN_MESH_COMPACT", "1") != "0", \
        "re-binned pass needs PYCHEMKIN_MESH_COMPACT on"
    warm(mesh_n, unit)
    # the zero-new-compiles claim: every shard_mapped rung program's
    # jit cache is frozen by warmup — the timed pass adds nothing
    progs = [p for ps in compaction._MESH_PROGRAM_CACHE.values()
             for p in ps]
    sizes_before = [p._cache_size() for p in progs]
    rebins0 = rec.snapshot(write=False)["counters"].get(
        "schedule.mesh_rebins", 0)
    jr_rebin: dict = {}
    t0 = time.time()
    t_r, ok_r, st_r = sweep(mesh_n, t_ends, job_report=jr_rebin)
    wall_rebin = time.time() - t0
    sizes_after = [p._cache_size() for p in progs]
    mesh_rebins = rec.snapshot(write=False)["counters"].get(
        "schedule.mesh_rebins", 0) - rebins0
    zero_new_compiles = sizes_before == sizes_after
    assert jr_rebin.get("schedule_compaction") is True, jr_rebin
    print(f"# rebin: {wall_rebin:.1f}s ({wall_rebin/B*1e3:.0f} "
          f"ms/elem), {mesh_rebins} re-bins, compiles "
          f"{'frozen' if zero_new_compiles else 'GREW'}",
          file=sys.stderr)

    # --- pass 2: mesh, sort-only (the pre-ISSUE-16 behaviour) -------
    os.environ["PYCHEMKIN_MESH_COMPACT"] = "0"
    try:
        jr_sort: dict = {}
        sweep(mesh_n, np.full(B, 1e-7))            # warm shard program
        t0 = time.time()
        t_s, ok_s, st_s = sweep(mesh_n, t_ends, job_report=jr_sort)
        wall_sort = time.time() - t0
    finally:
        del os.environ["PYCHEMKIN_MESH_COMPACT"]
    assert jr_sort.get("schedule_compaction") is not True, jr_sort
    print(f"# sort-only: {wall_sort:.1f}s ({wall_sort/B*1e3:.0f} "
          f"ms/elem)", file=sys.stderr)

    # --- pass 3: single-device compacted (the bit-identity oracle) --
    warm(mesh_1, 8)
    t0 = time.time()
    t_1, ok_1, st_1 = sweep(mesh_1, t_ends)
    wall_single = time.time() - t0
    print(f"# single-device: {wall_single:.1f}s", file=sys.stderr)

    t_r, ok_r, st_r, t_s, ok_s, st_s, t_1, ok_1, st_1 = map(
        np.asarray, (t_r, ok_r, st_r, t_s, ok_s, st_s, t_1, ok_1,
                     st_1))
    bit_vs_single = bool(
        np.array_equal(t_r, t_1, equal_nan=True)
        and np.array_equal(ok_r, ok_1) and np.array_equal(st_r, st_1))
    # the honest mesh-vs-single contract (see module docstring):
    # bitwise only where per-device and single-device program widths
    # lower identically; otherwise identical ok/status/finite
    # patterns off the budget boundary plus a tight deviation bound.
    bud = int(SolveStatus.BUDGET_EXHAUSTED)
    boundary = (st_r == bud) | (st_1 == bud)
    core = ~boundary
    both_1 = np.isfinite(t_r) & np.isfinite(t_1) & core
    rel_dev_single = (float(np.max(np.abs(t_r[both_1] - t_1[both_1])
                                   / np.abs(t_1[both_1])))
                      if both_1.any() else 0.0)
    match_vs_single = bool(
        np.array_equal(ok_r[core], ok_1[core])
        and np.array_equal(st_r[core], st_1[core])
        and np.array_equal(np.isfinite(t_r[core]),
                           np.isfinite(t_1[core]))
        and rel_dev_single < 1e-9)
    # vs the legacy shard program: same two-programs caveat as the
    # batch_efficiency rung (per-device blocks can run below the
    # 8-lane width-invariance floor) — record status agreement and
    # the measured deviation, never claim bitwise
    status_match_sort = bool(np.array_equal(ok_r, ok_s)
                             and np.array_equal(st_r, st_s))
    both = np.isfinite(t_r) & np.isfinite(t_s)
    rel_dev_sort = (float(np.max(np.abs(t_r[both] - t_s[both])
                                 / np.abs(t_r[both])))
                    if both.any() else 0.0)

    return {
        "tool": "bench_multichip",
        "platform": devices[0].platform,
        "forced_host_devices": True,
        "n_devices": n_devices,
        "mech": mech_name,
        "B": B,
        "seed": 0,
        "T_range": [700.0, 1500.0],
        "phi_range": [0.5, 2.0],
        "P_atm_range": [1.0, 2.0],
        "t_end": t_end,
        "rtol": rtol,
        "atol": atol,
        "max_steps": max_steps,
        "ladder": [int(w) for w in ladder],
        "round_len": compaction._round_len(),
        "calibration": calibration.probe(),
        "rebin_wall_s": round(wall_rebin, 3),
        "sort_only_wall_s": round(wall_sort, 3),
        "single_device_wall_s": round(wall_single, 3),
        "rebin_ms_per_elem": round(wall_rebin / B * 1e3, 3),
        "sort_only_ms_per_elem": round(wall_sort / B * 1e3, 3),
        "rebin_speedup": round(wall_sort / wall_rebin, 3),
        "mesh_rebins": int(mesh_rebins),
        "zero_new_compiles": zero_new_compiles,
        "jit_cache_entries": sum(sizes_after),
        "bit_identical_vs_single_device": bit_vs_single,
        "match_vs_single_device": match_vs_single,
        "times_max_rel_dev_vs_single_device": float(
            f"{rel_dev_single:.3g}"),
        "n_boundary_lanes": int(boundary.sum()),
        "n_status_mismatch_vs_single": int(
            np.sum(st_r != st_1)),
        "status_match_vs_sort_only": status_match_sort,
        "times_max_rel_dev_vs_sort_only": float(
            f"{rel_dev_sort:.3g}"),
        "n_ok": int(ok_r.sum()),
        "n_budget_capped": int(np.sum(
            st_r == int(SolveStatus.BUDGET_EXHAUSTED))),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mech", default="grisyn",
                   choices=["h2o2", "grisyn", "gri30"])
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--t-end", type=float, default=None,
                   help="horizon (default: the mech's bench protocol)")
    p.add_argument("--max-steps", type=int, default=10_000,
                   help="per-element step-attempt budget (the "
                        "batch_efficiency cap for super-marginal "
                        "lanes)")
    p.add_argument("--out", default="MULTICHIP_r06.json")
    args = p.parse_args(argv)

    _force_devices(args.devices)
    out = run_bench(args.mech, args.batch, args.devices, args.t_end,
                    args.max_steps)
    from pychemkin_tpu import telemetry
    telemetry.atomic_write_json(args.out, out)
    print(json.dumps(out))
    ok = (out["match_vs_single_device"]
          and out["zero_new_compiles"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
